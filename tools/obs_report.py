"""Render a human-readable run breakdown from a telemetry JSONL artifact.

Usage:

    python -m tools.obs_report runs.jsonl            # all runs
    python -m tools.obs_report runs.jsonl --run 3    # one run
    python -m tools.obs_report runs.jsonl --counters # counter totals only
    python -m tools.obs_report runs.jsonl --all      # every section
    python -m tools.obs_report runs.jsonl --trace X  # tools.trace_report
    python -m tools.obs_report --staticcheck         # lint health line

The artifact is produced by ``deequ_tpu.telemetry.configure(
jsonl_path=...)`` (or ``DEEQU_TPU_TELEMETRY_JSONL``); every finished
span, engine event, and run summary is one JSON line. See
docs/OBSERVABILITY.md for line shapes and the counter catalog.
``--staticcheck`` appends (or, without a path, just prints) the
one-line static-analysis summary from ``tools.staticcheck``
(docs/STATIC_ANALYSIS.md) so an ops report carries lint health next
to runtime health.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any, Dict, List, Optional

from deequ_tpu.telemetry import read_jsonl, summarize_phases


def load_runs(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The run_summary lines, in file order."""
    return [r for r in records if r.get("type") == "run_summary"]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def render_wire_diet(summary: Dict[str, Any]) -> str:
    """The streaming wire-diet line (docs/PERF.md): bytes/row raw vs
    encoded, the effective host->device link rate (raw bytes the
    encoded transfer REPRESENTS per second of put wall), and the
    dictionary-delta traffic. Empty string when the run shipped no
    encoded wire (resident runs, codecs off with no counter)."""
    counters = summary.get("counters", {})
    raw = float(counters.get("engine.wire_bytes_raw", 0))
    encoded = float(counters.get("engine.wire_bytes_encoded", 0))
    if encoded <= 0:
        return ""
    rows = max(
        (int(p.get("rows", 0)) for p in summary.get("passes", [])),
        default=0,
    )
    parts = []
    if rows > 0:
        parts.append(
            f"{raw / rows:.1f} -> {encoded / rows:.1f} bytes/row"
        )
    else:
        parts.append(f"{_fmt_bytes(raw)} -> {_fmt_bytes(encoded)}")
    parts.append(f"{raw / encoded:.2f}x thinner")
    phases = summarize_phases(summary.get("events", []))
    put_s = float(phases.get("put_s", 0.0)) if phases else 0.0
    if put_s > 0:
        parts.append(
            f"effective link {raw / put_s / (1024 * 1024):,.0f} MiB/s"
            f" (physical {encoded / put_s / (1024 * 1024):,.0f})"
        )
    deltas = int(counters.get("engine.dict_deltas", 0))
    if deltas:
        values = int(counters.get("engine.dict_delta_values", 0))
        parts.append(f"{deltas} dict delta(s), {values} value(s)")
    return "  wire diet: " + ", ".join(parts)


def render_ingest_pool(summary: Dict[str, Any]) -> str:
    """The r10 parallel-ingest line: worker count, per-stage busy
    fractions of the worker-second budget (decode/encode vs idle), and
    the reassembly stall — consumer wall spent waiting for the ordered
    head while later sequence numbers sat finished. Empty string when
    no scan in the run engaged the pool (workers=1 runs the legacy
    single prefetcher, which emits no ``ingest_pool`` event)."""
    pools = [
        e for e in summary.get("events", [])
        if e.get("event") == "ingest_pool"
    ]
    if not pools:
        return ""
    workers = max(int(e.get("workers", 0)) for e in pools)
    released = sum(int(e.get("released", 0)) for e in pools)
    wall = sum(float(e.get("wall_s", 0.0)) for e in pools)
    decode = sum(float(e.get("decode_s", 0.0)) for e in pools)
    encode = sum(float(e.get("encode_s", 0.0)) for e in pools)
    idle = sum(float(e.get("idle_s", 0.0)) for e in pools)
    stall = sum(float(e.get("stall_s", 0.0)) for e in pools)
    peak_bytes = max(
        int(e.get("peak_in_flight_bytes", 0)) for e in pools
    )
    parts = [f"{workers} worker(s), {released} batch(es)"]
    budget = wall * max(1, workers)  # worker-seconds available
    if budget > 0:
        parts.append(
            f"busy decode {100.0 * decode / budget:.0f}%"
            f" / encode {100.0 * encode / budget:.0f}%"
            f" / idle {100.0 * idle / budget:.0f}%"
        )
    parts.append(f"reassembly stall {stall:.3f}s")
    if peak_bytes > 0:
        parts.append(f"peak in-flight {_fmt_bytes(peak_bytes)}")
    return "  ingest pool: " + ", ".join(parts)


def render_egress(records: List[Dict[str, Any]]) -> str:
    """The row-level egress line (docs/EGRESS.md), one per sink run:
    how the rows split across the clean/quarantine parquet artifact,
    the outbound bytes per row (raw -> encoded), and the encode share —
    what fraction of the raw outbound bytes the wire actually carried.
    The ``rowlevel_egress`` event is emitted at finalize, AFTER the
    run's telemetry summary closes, so this reads top-level event
    lines. Empty string when no run streamed a row-level sink."""
    events = [
        r for r in records
        if r.get("type") == "event"
        and r.get("event") == "rowlevel_egress"
    ]
    if not events:
        # an artifact can hold resumes with no finalize yet (every
        # attempt so far was interrupted) — still worth a line
        return render_egress_resume(records)
    lines = []
    for e in events:
        clean = int(e.get("rows_clean", 0))
        quarantined = int(e.get("rows_quarantined", 0))
        raw = float(e.get("bytes_raw", 0))
        encoded = float(e.get("bytes_encoded", 0))
        rows = clean + quarantined
        parts = [f"{clean:,} clean / {quarantined:,} quarantined"]
        if rows > 0 and raw > 0:
            parts.append(
                f"{raw / rows:.1f} -> {encoded / rows:.1f} bytes/row out"
            )
            parts.append(f"encode share {100.0 * encoded / raw:.0f}%")
        status = str(e.get("status", "?"))
        if status != "complete":
            parts.append(f"status {status}")
        n_constraints = int(e.get("constraints", 0))
        if n_constraints:
            parts.append(f"{n_constraints} constraint(s)")
        tenant = str(e.get("tenant", ""))
        if tenant:
            parts.append(f"tenant {tenant}")
        lines.append("egress: " + ", ".join(parts))
    resume_line = render_egress_resume(records)
    if resume_line:
        lines.append(resume_line)
    return "\n".join(lines)


def render_egress_resume(records: List[Dict[str, Any]]) -> str:
    """The durable-egress resume line (docs/EGRESS.md "Durable
    egress"), one per artifact: how many interrupted sink runs resumed
    from their span cursor, and the exactly-once pin —
    ``rows_replayed`` summed over every resume, which the
    flush-then-cursor ordering holds at 0. ``egress_resumed`` events
    fire DURING the scan, so each lands in its run summary's event
    list AND as a top-level event line; count the summary copy and
    only fall back to top-level lines for runs with no summary (a
    scan outside a run context, or a summary lost to a crash)."""
    resumed: List[Dict[str, Any]] = []
    summarized_runs = set()
    for summary in load_runs(records):
        summarized_runs.add(summary.get("run_id"))
        resumed.extend(
            e for e in summary.get("events", [])
            if e.get("event") == "egress_resumed"
        )
    resumed.extend(
        r for r in records
        if r.get("type") == "event"
        and r.get("event") == "egress_resumed"
        and r.get("run_id") not in summarized_runs
    )
    if not resumed:
        return ""
    replayed = sum(int(e.get("rows_replayed", 0)) for e in resumed)
    recovered = sum(
        int(e.get("rows_clean", 0)) + int(e.get("rows_quarantined", 0))
        for e in resumed
    )
    parts = [
        f"{len(resumed)} resume(s) from span cursor",
        f"{recovered:,} rows already durable",
        f"{replayed:,} rows replayed"
        + (" (exactly-once held)" if replayed == 0 else " (DUPLICATES)"),
    ]
    return "egress-resume: " + ", ".join(parts)


def render_run(summary: Dict[str, Any]) -> str:
    """One run's breakdown: pass table, wall decomposition, counters."""
    lines = []
    run_id = summary.get("run_id", "?")
    name = summary.get("name", "run")
    wall = float(summary.get("wall_s", 0.0))
    lines.append(f"run {run_id} ({name}): wall {wall:.3f}s")

    data_passes = summary.get("counters", {}).get("engine.data_passes")
    if data_passes is not None:
        # the one-pass-spill headline number: a mixed suite (scalars +
        # dense grouping + spill plans) should read 1 here
        lines.append(f"  passes over source: {int(data_passes)}")

    wire_line = render_wire_diet(summary)
    if wire_line:
        lines.append(wire_line)

    pool_line = render_ingest_pool(summary)
    if pool_line:
        lines.append(pool_line)

    passes = summary.get("passes", [])
    if passes:
        lines.append("  passes:")
        for p in passes:
            p_wall = float(p.get("wall_s", 0.0))
            rows = int(p.get("rows", 0))
            rps = rows / p_wall if p_wall > 0 else 0.0
            share = 100.0 * p_wall / wall if wall > 0 else 0.0
            lines.append(
                f"    {p.get('pass', '?'):<10} {p_wall:8.3f}s"
                f"  ({share:5.1f}% of wall)"
                f"  rows={rows:<10} analyzers={p.get('num_analyzers', 0):<4}"
                f" {rps:,.0f} rows/s"
            )

    phases = summarize_phases(summary.get("events", []))
    if phases:
        lines.append("  scan wall decomposition "
                     f"({phases.get('scan_passes', 0)} scan(s)):")
        for key in ("host_wait_s", "put_s", "dispatch_s", "first_step_s",
                    "sync_s"):
            if key in phases:
                lines.append(f"    {key:<14} {phases[key]:8.3f}s")

    res_counters = summary.get("counters", {})
    res_keys = (
        "engine.batch_retries",
        "engine.batches_quarantined",
        "engine.checkpoints_written",
        "engine.resumes",
        "engine.stalls_detected",
        "engine.deadline_exceeded",
        "engine.runs_cancelled",
        "engine.runs_queued",
        "engine.oom_events",
        "engine.batch_size_backoffs",
        "engine.spill_downgrades",
    )
    if any(res_counters.get(k) for k in res_keys):
        lines.append("  resilience:")
        for k in res_keys:
            v = res_counters.get(k)
            if v:
                lines.append(f"    {k:<32} {int(v)}")
        for e in summary.get("events", []):
            if e.get("event") == "batch_quarantined":
                lines.append(
                    f"    quarantined batch {e.get('batch_index')}:"
                    f" {e.get('error_class')}"
                    f" (rows={e.get('rows')},"
                    f" attempts={e.get('attempts')})"
                )
            elif e.get("event") == "scan_stalled":
                lines.append(
                    f"    stall detected: no batch for"
                    f" {e.get('stall_s')}s"
                    f" (stalls={e.get('stalls')})"
                )
            elif e.get("event") == "run_cancelled":
                lines.append(
                    f"    run interrupted ({e.get('kind')}):"
                    f" {e.get('reason')}"
                    f" [batch={e.get('batch_index')},"
                    f" checkpointed={e.get('checkpointed')}]"
                )
            elif e.get("event") == "scan_memory_pressure":
                action = e.get("action")
                if action == "oom":
                    lines.append(
                        f"    memory pressure ({e.get('origin')}) at"
                        f" {e.get('stage')} batch {e.get('batch_index')}"
                        f" (rows={e.get('rows')})"
                    )
                elif action in ("backoff", "heal"):
                    lines.append(
                        f"    batch size {action}:"
                        f" {e.get('from_rows')} ->"
                        f" {e.get('effective_rows')} rows"
                    )
                elif action == "exhausted":
                    lines.append(
                        f"    backoff exhausted at batch"
                        f" {e.get('batch_index')}"
                        f" (floor={e.get('effective_rows')} rows)"
                    )
                elif action == "spill-downgrade":
                    lines.append(
                        f"    spill downgrade"
                        f" ({','.join(e.get('columns', []))}):"
                        f" {e.get('stage')} -> {e.get('path')}"
                    )

    spills = [
        e for e in summary.get("events", [])
        if e.get("event") == "grouping_spill"
    ]
    if spills:
        lines.append("  grouping spills:")
        for e in spills:
            lines.append(
                f"    {','.join(e.get('columns', []))} -> {e.get('path')}"
            )

    counters = summary.get("counters", {})
    if counters:
        lines.append("  counters (delta over run):")
        for k in sorted(counters):
            v = counters[k]
            shown = _fmt_bytes(v) if k == "transfer.bytes" else str(v)
            lines.append(f"    {k:<32} {shown}")
    return "\n".join(lines)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(
        len(sorted_vals) - 1,
        max(0, int(round(q * (len(sorted_vals) - 1)))),
    )
    return sorted_vals[idx]


def render_service(records: List[Dict[str, Any]]) -> str:
    """The ``service:`` section: per-tenant run counts, queue-wait
    percentiles, plan-cache hits vs recompiles, and dataset-cache
    hits/evictions — everything an operator needs to answer "is the
    warm path actually warm" from one JSONL artifact. Empty string when
    the artifact has no service events."""
    events = [r for r in records if r.get("type") == "event"]
    service_events = [
        e for e in events
        if str(e.get("event", "")).startswith("service_")
    ]
    if not service_events:
        return ""

    lines = ["service:"]

    # per-tenant run counts, split by outcome
    by_tenant: Dict[str, Dict[str, int]] = {}
    for e in service_events:
        if e.get("event") != "service_run_finished":
            continue
        tenant = str(e.get("tenant", "?"))
        status = str(e.get("status", "?"))
        by_tenant.setdefault(tenant, {})
        by_tenant[tenant][status] = by_tenant[tenant].get(status, 0) + 1
    rejected = [
        e for e in service_events
        if e.get("event") == "service_run_rejected"
    ]
    for e in rejected:
        tenant = str(e.get("tenant", "?"))
        by_tenant.setdefault(tenant, {})
        by_tenant[tenant]["rejected"] = (
            by_tenant[tenant].get("rejected", 0) + 1
        )
    if by_tenant:
        lines.append("  runs by tenant:")
        for tenant in sorted(by_tenant):
            outcomes = by_tenant[tenant]
            total = sum(outcomes.values())
            detail = ", ".join(
                f"{k}={v}" for k, v in sorted(outcomes.items())
            )
            lines.append(f"    {tenant:<16} {total:<4} ({detail})")

    # queue-wait percentiles from the started events
    waits = sorted(
        float(e.get("queue_wait_s", 0.0))
        for e in service_events
        if e.get("event") == "service_run_started"
    )
    if waits:
        lines.append(
            f"  queue wait ({len(waits)} run(s)):"
            f" p50={_percentile(waits, 0.50):.3f}s"
            f" p90={_percentile(waits, 0.90):.3f}s"
            f" p99={_percentile(waits, 0.99):.3f}s"
            f" max={waits[-1]:.3f}s"
        )

    # plan cache: warmed tokens vs steady-state hits/recompiles. The
    # authoritative hit/miss deltas live in the run summaries' counter
    # blocks (engine.plan_cache.*); warmup passes also produce run
    # summaries, so split on the warmed event's position in the file.
    warmed = [
        e for e in service_events
        if e.get("event") == "service_plans_warmed"
    ]
    plan_hits = 0.0
    plan_misses = 0.0
    for r in load_runs(records):
        counters = r.get("counters", {})
        plan_hits += counters.get("engine.plan_cache.hits", 0)
        plan_misses += counters.get("engine.plan_cache.misses", 0)
    lines.append(
        f"  plan cache: hits={int(plan_hits)}"
        f" compiles={int(plan_misses)}"
        + (
            f" (warmed"
            f" {sum(len(e.get('tokens', [])) for e in warmed)}"
            f" plan(s) at startup)"
            if warmed
            else ""
        )
    )

    # dataset cache: placements (misses) vs shared leases (hits) vs
    # watermark evictions
    leases = [
        e for e in service_events
        if e.get("event") == "service_dataset_leased"
    ]
    if leases:
        hits = sum(1 for e in leases if e.get("cache_hit"))
        evictions = sum(
            1 for e in service_events
            if e.get("event") == "service_dataset_evicted"
        )
        lines.append(
            f"  dataset cache: hits={hits}"
            f" placements={len(leases) - hits}"
            f" evictions={evictions}"
        )
        keys = sorted(
            {str(e.get("dataset_key", "?")) for e in leases}
        )
        lines.append(f"    keys: {', '.join(keys)}")

    # scan coalescing (docs/SERVICE.md "Scan coalescing"): how many
    # runs shared a superset scan, the source passes that saved, and
    # whether any superset fell back to independent execution
    coalesced = [
        e for e in events if e.get("event") == "runs_coalesced"
    ]
    if coalesced:
        members = [int(e.get("members", 0)) for e in coalesced]
        saved = sum(m - 1 for m in members)
        fallbacks = sum(
            1 for e in events if e.get("event") == "coalesce_fallback"
        )
        waits_max = max(
            float(e.get("queue_wait_s_max", 0.0)) for e in coalesced
        )
        lines.append(
            f"  coalescing: {sum(members)} run(s) over"
            f" {len(coalesced)} superset scan(s)"
            f" (passes saved={saved},"
            f" max window wait={waits_max:.3f}s"
            + (f", fallbacks={fallbacks}" if fallbacks else "")
            + ")"
        )

    # checkpoint-conserving preemption + autoscaling (docs/SERVICE.md
    # "Preemption and autoscaling"): did interactive demand displace
    # batch work, how much scan progress the cursors carried across,
    # and what the control loop actuated
    preempted = [
        e for e in events if e.get("event") == "service_run_preempted"
    ]
    if preempted:
        resumed = sum(
            1 for e in events
            if e.get("event") == "service_run_resumed"
        )
        conserved = sum(
            int(e.get("batch_index", 0)) for e in preempted
            if e.get("checkpointed")
        )
        lines.append(
            f"  preemption: {len(preempted)} preempted,"
            f" {resumed} resumed"
            f" (batches conserved={conserved})"
        )
    adjustments = [
        e for e in events if e.get("event") == "autoscale_adjustment"
    ]
    if adjustments:
        by_knob: Dict[str, int] = {}
        for e in adjustments:
            knob = str(e.get("knob", "?"))
            by_knob[knob] = by_knob.get(knob, 0) + 1
        knobs = ", ".join(
            f"{k} x{c}" for k, c in sorted(by_knob.items())
        )
        lines.append(
            f"  autoscale: {len(adjustments)} adjustment(s) ({knobs})"
        )

    # drains / rejections worth an operator's attention
    drains = [
        e for e in service_events
        if e.get("event") == "service_drained"
    ]
    for e in drains:
        lines.append(
            f"  drained {e.get('drained', 0)} queued run(s):"
            f" {e.get('reason', '?')}"
        )
    deadline_rejects = sum(
        1 for e in rejected
        if "deadline" in str(e.get("reason", ""))
    )
    if deadline_rejects:
        lines.append(
            f"  deadline-expired while queued: {deadline_rejects}"
        )
    return "\n".join(lines)


def render_placement(records: List[Dict[str, Any]]) -> str:
    """The ``placement:`` section (docs/SERVICE.md "Elastic
    placement"): how many runs were placed, the slice-size
    distribution, lease-wait percentiles, corrupt-compile-cache
    discards, and the per-shape plan-cache hit/compile split — the
    elastic acceptance question ("is every shape compile-free?") from
    one JSONL artifact. Empty string when nothing was placed."""
    events = [r for r in records if r.get("type") == "event"]
    placed = [e for e in events if e.get("event") == "run_placed"]

    counters: Dict[str, float] = {}
    for r in load_runs(records):
        for k, v in r.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    per_shape_keys = [
        k for k in counters
        if k.startswith("engine.plan_cache.per_shape.")
    ]
    if not placed and not per_shape_keys:
        return ""

    lines = ["placement:"]
    if placed:
        by_ndev: Dict[int, int] = {}
        for e in placed:
            ndev = int(e.get("ndev", 0))
            by_ndev[ndev] = by_ndev.get(ndev, 0) + 1
        dist = ", ".join(
            f"{n}dev x{c}" for n, c in sorted(by_ndev.items())
        )
        lines.append(f"  placements: {len(placed)} ({dist})")
        waits = sorted(
            float(e.get("lease_wait_s", 0.0)) for e in placed
        )
        lines.append(
            f"  lease wait: p50={_percentile(waits, 0.50):.3f}s"
            f" p90={_percentile(waits, 0.90):.3f}s"
            f" p99={_percentile(waits, 0.99):.3f}s"
            f" max={waits[-1]:.3f}s"
        )
        # which devices actually saw work — disjointness at a glance
        device_sets = sorted(
            {str(e.get("device_ids", "?")) for e in placed}
        )
        lines.append(f"  slices used: {'; '.join(device_sets)}")
    if per_shape_keys:
        lines.append("  plan cache per shape:")
        labels = sorted(
            {
                k[len("engine.plan_cache.per_shape."):].rsplit(".", 1)[0]
                for k in per_shape_keys
            }
        )
        for label in labels:
            hits = int(
                counters.get(
                    f"engine.plan_cache.per_shape.{label}.hits", 0
                )
            )
            misses = int(
                counters.get(
                    f"engine.plan_cache.per_shape.{label}.misses", 0
                )
            )
            lines.append(
                f"    {label:<8} hits={hits} compiles={misses}"
            )
    corrupt = int(counters.get("engine.compile_cache_corrupt", 0)) or sum(
        1 for e in events if e.get("event") == "compile_cache_corrupt"
    )
    if corrupt:
        lines.append(
            f"  corrupt compile-cache entries discarded: {corrupt}"
        )
    return "\n".join(lines)


def render_crash_recovery(records: List[Dict[str, Any]]) -> str:
    """The ``crash recovery:`` section (docs/RESILIENCE.md): child
    crashes by signal, relaunches and checkpoint resumes, crash loops
    and breaker trips, journaled runs re-admitted after a daemon
    restart, and load-shed submissions — the whole process-level fault
    story from one JSONL artifact. Empty string when the artifact has
    no crash/recovery signals."""
    counters: Dict[str, float] = {}
    for r in load_runs(records):
        for k, v in r.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    events = [r for r in records if r.get("type") == "event"]
    crashes = [e for e in events if e.get("event") == "child_crashed"]
    recovered = [
        e for e in events if e.get("event") == "service_run_recovered"
    ]
    shed = [
        e for e in events if e.get("event") == "service_submission_shed"
    ]
    breaker_opens = [
        e for e in events if e.get("event") == "crash_breaker_open"
    ]
    torn = [e for e in events if e.get("event") == "journal_truncated"]

    child_crashes = int(counters.get("engine.child_crashes", 0)) or len(
        crashes
    )
    runs_recovered = int(
        counters.get("service.runs_recovered", 0)
    ) or len(recovered)
    shed_count = int(
        counters.get("service.submissions_shed", 0)
    ) or len(shed)
    if not any(
        (child_crashes, runs_recovered, shed_count, breaker_opens, torn)
    ):
        return ""

    lines = ["crash recovery:"]
    if child_crashes:
        by_signal: Dict[str, int] = {}
        for e in crashes:
            sig = str(e.get("signal") or "exit")
            by_signal[sig] = by_signal.get(sig, 0) + 1
        sig_detail = (
            " ("
            + ", ".join(
                f"{k}={v}" for k, v in sorted(by_signal.items())
            )
            + ")"
            if by_signal
            else ""
        )
        lines.append(f"  child crashes: {child_crashes}{sig_detail}")
        relaunches = int(counters.get("engine.child_relaunches", 0))
        resumes = int(counters.get("engine.crash_resumes", 0))
        if relaunches or resumes:
            lines.append(
                f"  relaunches: {relaunches},"
                f" completed after resume: {resumes}"
            )
        loops = int(counters.get("engine.crash_loops", 0))
        if loops:
            lines.append(f"  crash loops declared: {loops}")
    trips = int(counters.get("engine.breaker_trips", 0)) or len(
        breaker_opens
    )
    if trips:
        keys = sorted(
            {str(e.get("key", "?")) for e in breaker_opens}
        )
        lines.append(
            f"  breaker trips: {trips}"
            + (f" (keys: {', '.join(keys)})" if keys else "")
        )
    if runs_recovered:
        resumed = sum(
            1 for e in recovered if e.get("last_checkpoint")
        )
        lines.append(
            f"  runs recovered after restart: {runs_recovered}"
            f" ({resumed} from a checkpoint cursor)"
        )
    if shed_count:
        reasons: Dict[str, int] = {}
        for e in shed:
            reason = str(e.get("reason", "?"))
            reasons[reason] = reasons.get(reason, 0) + 1
        reason_detail = (
            " ("
            + ", ".join(
                f"{k}={v}" for k, v in sorted(reasons.items())
            )
            + ")"
            if reasons
            else ""
        )
        lines.append(
            f"  submissions shed: {shed_count}{reason_detail}"
        )
    for e in torn:
        lines.append(
            f"  journal truncated at seq {e.get('at_seq', '?')}:"
            f" torn tail dropped on replay"
        )
    return "\n".join(lines)


def render_fleet(records: List[Dict[str, Any]]) -> str:
    """The ``fleet failover:`` section (docs/SERVICE.md "Fleet
    failover"): lease membership, expiries and adoptions with their
    staleness ages, orphan runs re-admitted, zombie writes fenced, and
    poison quarantines — the fleet-level fault story from one JSONL
    artifact. Empty string when the artifact has no fleet signals."""
    counters: Dict[str, float] = {}
    for r in load_runs(records):
        for k, v in r.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
    events = [r for r in records if r.get("type") == "event"]
    claimed = [e for e in events if e.get("event") == "fleet_lease_claimed"]
    retired = [e for e in events if e.get("event") == "fleet_lease_retired"]
    expired = [e for e in events if e.get("event") == "fleet_lease_expired"]
    adoptions = [e for e in events if e.get("event") == "fleet_adoption"]
    races = [
        e for e in events if e.get("event") == "fleet_adoption_race_lost"
    ]
    run_adopted = [
        e for e in events if e.get("event") == "service_run_adopted"
    ]
    fenced = [e for e in events if e.get("event") == "fleet_write_fenced"]
    poisoned = [e for e in events if e.get("event") == "fleet_run_poisoned"]

    adoption_count = int(counters.get("service.fleet.adoptions", 0)) or len(
        adoptions
    )
    fenced_count = int(
        counters.get("service.fleet.fenced_writes", 0)
    ) or len(fenced)
    poison_count = int(
        counters.get("service.fleet.poisoned_runs", 0)
    ) or len(poisoned)
    if not any(
        (claimed, retired, expired, adoption_count, fenced_count,
         poison_count)
    ):
        return ""

    lines = ["fleet failover:"]
    if claimed or retired:
        members = sorted(
            {str(e.get("replica", "?")) for e in claimed}
        )
        retired_ids = sorted(
            {str(e.get("replica", "?")) for e in retired}
        )
        line = f"  replicas: {len(members)}"
        if members:
            line += f" ({', '.join(members)})"
        if retired_ids:
            line += f", retired cleanly: {', '.join(retired_ids)}"
        lines.append(line)
    for e in expired:
        lines.append(
            f"  lease expired: {e.get('replica', '?')}"
            f" epoch {e.get('epoch', '?')}"
            f" after {e.get('stale_for_s', '?')}s"
            f" (observer {e.get('observer', '?')})"
        )
    if adoption_count:
        for e in adoptions:
            lines.append(
                f"  adoption: {e.get('adopter', '?')} claimed"
                f" {e.get('replica', '?')} at epoch"
                f" {e.get('epoch', '?')}"
                f" (stale {e.get('stale_for_s', '?')}s)"
            )
        if not adoptions:
            lines.append(f"  adoptions: {adoption_count}")
    if races:
        losers = sorted({str(e.get("loser", "?")) for e in races})
        lines.append(
            f"  adoption races lost: {len(races)}"
            f" (losers: {', '.join(losers)})"
        )
    runs_count = int(
        counters.get("service.fleet.runs_adopted", 0)
    ) or len(run_adopted)
    if runs_count:
        resumed = sum(1 for e in run_adopted if e.get("last_checkpoint"))
        lines.append(
            f"  orphan runs re-admitted: {runs_count}"
            f" ({resumed} from a checkpoint cursor)"
        )
    if fenced_count:
        zombies = sorted({str(e.get("replica", "?")) for e in fenced})
        lines.append(
            f"  zombie writes fenced: {fenced_count}"
            + (f" (replicas: {', '.join(zombies)})" if zombies else "")
        )
    drops = int(counters.get("service.fleet.child_checkpoint_drops", 0))
    if drops:
        lines.append(f"  fenced child checkpoint drops: {drops}")
    if poison_count:
        keys = sorted(
            {str(e.get("plan_key", "?")) for e in poisoned}
        )
        lines.append(
            f"  poison quarantines: {poison_count}"
            + (f" (plans: {', '.join(keys)})" if keys else "")
        )
    return "\n".join(lines)


def render_staticcheck(root: Optional[str] = None) -> str:
    """One-line static-analysis health summary, e.g. ``staticcheck: 0
    finding(s), 29 waived across 12 rules (clean)``."""
    from tools.staticcheck import all_rules, run_analyzers, summarize

    from_root = root
    if from_root is None:
        from tools.staticcheck import default_root

        from_root = default_root()
    stats = summarize(run_analyzers(from_root))
    verdict = (
        "clean"
        if stats["unwaived"] == 0
        else "FAILING — run python -m tools.staticcheck"
    )
    return (
        f"staticcheck: {stats['unwaived']} finding(s), "
        f"{stats['waived']} waived across {len(all_rules())} rules "
        f"({verdict})"
    )


def render_all(records: List[Dict[str, Any]]) -> str:
    """Every section in one report: run breakdowns with all the
    optional sections, counter totals, the trace critical-path
    aggregate (tools.trace_report), and the staticcheck health line."""
    parts = [render(records)]
    counters = render(records, counters_only=True)
    if counters:
        parts.append(counters)
    from tools.trace_report import render as render_traces

    traces = render_traces(records)
    if not traces.startswith("no traced spans"):
        parts.append(traces)
    parts.append(render_staticcheck())
    return "\n\n".join(p for p in parts if p)


def render(
    records: List[Dict[str, Any]],
    run_id: Optional[int] = None,
    counters_only: bool = False,
    service_only: bool = False,
    crashes_only: bool = False,
    placement_only: bool = False,
    fleet_only: bool = False,
) -> str:
    if service_only:
        section = render_service(records)
        return section or "no service events in artifact"
    if crashes_only:
        section = render_crash_recovery(records)
        return section or "no crash/recovery signals in artifact"
    if placement_only:
        section = render_placement(records)
        return section or "no placement signals in artifact"
    if fleet_only:
        section = render_fleet(records)
        return section or "no fleet signals in artifact"
    runs = load_runs(records)
    if run_id is not None:
        runs = [r for r in runs if r.get("run_id") == run_id]
        if not runs:
            return f"no run_summary with run_id={run_id}"
    if counters_only:
        totals: Dict[str, float] = {}
        for r in runs:
            for k, v in r.get("counters", {}).items():
                totals[k] = totals.get(k, 0) + v
        lines = [f"counter totals over {len(runs)} run(s):"]
        for k in sorted(totals):
            v = totals[k]
            shown = _fmt_bytes(v) if k == "transfer.bytes" else str(int(v))
            lines.append(f"  {k:<32} {shown}")
        return "\n".join(lines)
    if not runs:
        n_spans = sum(1 for r in records if r.get("type") == "span")
        n_events = sum(1 for r in records if r.get("type") == "event")
        return (
            f"no run summaries in artifact ({n_spans} spans, "
            f"{n_events} events) — was a run context "
            "(telemetry.run(...)) active?"
        )
    body = "\n\n".join(render_run(r) for r in runs)
    if run_id is None:
        egress_section = render_egress(records)
        if egress_section:
            body = body + "\n\n" + egress_section
        section = render_service(records)
        if section:
            body = body + "\n\n" + section
        placement_section = render_placement(records)
        if placement_section:
            body = body + "\n\n" + placement_section
        crash_section = render_crash_recovery(records)
        if crash_section:
            body = body + "\n\n" + crash_section
        fleet_section = render_fleet(records)
        if fleet_section:
            body = body + "\n\n" + fleet_section
    return body


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Render run breakdowns from a telemetry JSONL artifact"
    )
    parser.add_argument(
        "path", nargs="?", default=None, help="telemetry JSONL file"
    )
    parser.add_argument(
        "--run", type=int, default=None, help="render only this run_id"
    )
    parser.add_argument(
        "--counters", action="store_true",
        help="print only counter totals across runs",
    )
    parser.add_argument(
        "--service", action="store_true",
        help="print only the multi-tenant service section",
    )
    parser.add_argument(
        "--crashes", action="store_true",
        help="print only the crash isolation / recovery section",
    )
    parser.add_argument(
        "--placement", action="store_true",
        help="print only the elastic device placement section",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="print only the fleet failover section (leases, "
        "adoptions, fencing, poison quarantines)",
    )
    parser.add_argument(
        "--staticcheck", action="store_true",
        help="append the one-line static-analysis summary "
        "(tools.staticcheck); usable without a JSONL path",
    )
    parser.add_argument(
        "--all", action="store_true",
        help="print every section: run breakdowns, counter totals, "
        "the trace critical-path aggregate, and the staticcheck line",
    )
    parser.add_argument(
        "--trace", default=None, metavar="RUN",
        help="delegate to tools.trace_report for this trace_id or "
        "submission run_id (the per-run waterfall + critical path)",
    )
    args = parser.parse_args(argv)
    if args.path is None:
        if not args.staticcheck:
            parser.error("a telemetry JSONL path is required "
                         "(or pass --staticcheck)")
        print(render_staticcheck())
        return 0
    try:
        records = read_jsonl(args.path)
    except OSError as exc:
        print(f"cannot read {args.path}: {exc}", file=sys.stderr)
        return 2
    if args.trace is not None:
        from tools.trace_report import render as render_traces

        print(render_traces(records, run=args.trace))
        return 0
    if args.all:
        print(render_all(records))
        return 0
    print(render(
        records,
        run_id=args.run,
        counters_only=args.counters,
        service_only=args.service,
        crashes_only=args.crashes,
        placement_only=args.placement,
        fleet_only=args.fleet,
    ))
    if args.staticcheck:
        print(render_staticcheck())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
