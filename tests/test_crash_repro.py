"""The crash-repro bisection core (tools/crash_repro.py) — pure-logic
tests with a scripted probe; no children are ever spawned."""

from tools.crash_repro import BASE_CONFIG, MIN_BATCH, MIN_ROWS, bisect_crash


class _Probe:
    """Deterministic probe: ``rule(cfg) -> bool`` decides the crash."""

    def __init__(self, rule):
        self.rule = rule
        self.calls = []

    def __call__(self, cfg):
        self.calls.append(dict(cfg))
        return {"crashed": bool(self.rule(cfg))}


def test_no_crash_at_baseline_short_circuits():
    probe = _Probe(lambda cfg: False)
    verdict = bisect_crash(probe)
    assert verdict["reproduced"] is False
    assert verdict["narrowest"] is None
    assert verdict["xla_cache_implicated"] is False
    assert len(probe.calls) == 1  # baseline only, no bisection
    assert verdict["baseline"] == BASE_CONFIG


def test_cache_implicated_when_cache_off_stops_crashing():
    # crash needs the cache AND a big-enough batch AND enough rows
    def rule(cfg):
        return (
            cfg["xla_cache"]
            and cfg["batch_size"] >= (1 << 18)
            and cfg["rows"] >= 250_000
        )

    verdict = bisect_crash(_Probe(rule))
    assert verdict["reproduced"] is True
    assert verdict["xla_cache_implicated"] is True
    narrowest = verdict["narrowest"]
    # the cache stays ON in the narrowest config (turning it off left
    # the reproducing family), and every other dimension is minimal
    assert narrowest["xla_cache"] is True
    assert narrowest["batch_size"] == 1 << 18
    assert narrowest["rows"] == 250_000
    assert narrowest["ingest_workers"] == 1  # serial path still crashes
    # the narrowest config was actually observed to crash
    labels = [t["label"] for t in verdict["trials"]]
    assert labels[0] == "baseline"
    assert "xla_cache_off" in labels


def test_cache_innocent_keeps_cache_off_as_narrower():
    verdict = bisect_crash(_Probe(lambda cfg: True))
    assert verdict["xla_cache_implicated"] is False
    narrowest = verdict["narrowest"]
    # crashes either way, so cache-off is the narrower claim
    assert narrowest["xla_cache"] is False
    # always-crash bottoms out at the floors, and terminates
    assert narrowest["batch_size"] >= MIN_BATCH
    assert narrowest["batch_size"] < 2 * MIN_BATCH
    assert narrowest["rows"] >= MIN_ROWS
    assert narrowest["rows"] < 2 * MIN_ROWS


def test_serial_ingest_not_kept_when_it_stops_crashing():
    # crash requires parallel ingest (workers != 1)
    verdict = bisect_crash(_Probe(lambda cfg: cfg["ingest_workers"] != 1))
    assert verdict["reproduced"] is True
    assert verdict["narrowest"]["ingest_workers"] == BASE_CONFIG[
        "ingest_workers"
    ]


def test_trial_log_carries_probe_outcome():
    probe = _Probe(lambda cfg: False)
    bisect = bisect_crash(probe, dict(BASE_CONFIG, rows=123_456))
    trial = bisect["trials"][0]
    assert trial["config"]["rows"] == 123_456
    assert trial["outcome"] == {"crashed": False}
