"""Operational records: the system's own run metrics, persisted through
the SAME MetricsRepository as data-quality metrics.

The VLDB'18 deequ paper frames the system around metric time series;
here the monitor monitors itself: each repository-persisted run also
stores a small set of ``Entity.DATASET``-scoped DoubleMetrics (wall,
rows/sec, bytes shipped, cache hit counts, spill counts) under the same
``ResultKey`` — so the existing ``anomalydetection/`` strategies can
alert when e.g. rows/sec or bytes/row regresses across runs, with zero
new query machinery (``repository.load().for_analyzers([
OperationalAnalyzer("rows_per_sec")])`` is a plain metric series).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from deequ_tpu.analyzers.base import (
    Analyzer,
    MetricCalculationException,
)
from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.utils.trylike import Success

# the catalog of per-run operational metrics (docs/OBSERVABILITY.md)
OPERATIONAL_METRICS = (
    "wall_s",            # whole-run wall (run capture root)
    "pass_wall_s",       # sum of per-pass walls
    "rows",              # rows scanned (max over passes)
    "rows_per_sec",      # rows / wall_s
    "transfer_bytes",    # host->device bytes shipped during the run
    "bytes_per_row",     # transfer_bytes / rows
    "plan_cache_hits",
    "plan_cache_misses",
    "traces",            # fused-update retraces
    "spill_events",      # grouping spill/fallback decisions
)


@dataclass(frozen=True)
class OperationalAnalyzer(Analyzer):
    """Pseudo-analyzer keying one operational metric in the repository.

    Never runs against data — it exists so operational records ride the
    ordinary AnalysisResult serde/query path (repository/serde.py
    registers it) and anomaly strategies can load their series."""

    metric: str

    @property
    def name(self) -> str:
        return "Operational"

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    @property
    def instance(self) -> str:
        return self.metric

    def compute_metric_from_state(self, state: Optional[Any]) -> Metric:
        raise MetricCalculationException(
            "OperationalAnalyzer is repository-only; its values come "
            "from telemetry run summaries, never from data"
        )


def operational_values(summary: Optional[Dict[str, Any]]) -> Dict[str, float]:
    """Flatten a telemetry run summary into the operational metric
    values worth trending across runs."""
    if not summary:
        return {}
    passes = summary.get("passes", [])
    counters = summary.get("counters", {})
    wall = float(summary.get("wall_s", 0.0))
    rows = max((int(p.get("rows", 0)) for p in passes), default=0)
    values: Dict[str, float] = {
        "wall_s": wall,
        "pass_wall_s": float(sum(p.get("wall_s", 0.0) for p in passes)),
        "rows": float(rows),
        "transfer_bytes": float(counters.get("transfer.bytes", 0)),
        "plan_cache_hits": float(counters.get("engine.plan_cache.hits", 0)),
        "plan_cache_misses": float(
            counters.get("engine.plan_cache.misses", 0)
        ),
        "traces": float(counters.get("engine.traces", 0)),
        "spill_events": float(
            sum(
                v
                for k, v in counters.items()
                if k.startswith("grouping.spill.")
            )
        ),
    }
    if rows and wall > 0:
        values["rows_per_sec"] = rows / wall
        values["bytes_per_row"] = values["transfer_bytes"] / rows
    return values


def operational_metrics(
    summary: Optional[Dict[str, Any]],
) -> Dict[Analyzer, Metric]:
    """Build the {OperationalAnalyzer -> DoubleMetric} map persisted
    alongside a run's data-quality metrics (empty when telemetry was
    disabled for the run)."""
    return {
        OperationalAnalyzer(name): DoubleMetric(
            Entity.DATASET, "Operational", name, Success(float(value))
        )
        for name, value in operational_values(summary).items()
    }


def slo_metrics(
    snapshot: Optional[Dict[str, Any]],
) -> Dict[Analyzer, Metric]:
    """Flatten an ``SloTracker.snapshot()`` into repository-persistable
    operational records: per class ``slo.class.<name>.attained`` and
    ``.budget_burn`` (per tenant under ``slo.tenant.<name>.*``) — so
    the anomaly strategies can alert on p99 drift from the SAME metric
    series machinery as everything else."""
    if not snapshot:
        return {}
    out: Dict[Analyzer, Metric] = {}
    for scope, key in (("class", "classes"), ("tenant", "tenants")):
        for name, stats in (snapshot.get(key) or {}).items():
            for field in ("attained", "budget_burn"):
                value = stats.get(field)
                if value is None or value != value or value in (
                    float("inf"), float("-inf")
                ):
                    continue
                instance = f"slo.{scope}.{name}.{field}"
                out[OperationalAnalyzer(instance)] = DoubleMetric(
                    Entity.DATASET, "Operational", instance,
                    Success(float(value)),
                )
    return out
