from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintDecorator,
    ConstraintResult,
    ConstraintStatus,
    NamedConstraint,
)

__all__ = [
    "AnalysisBasedConstraint",
    "Constraint",
    "ConstraintDecorator",
    "ConstraintResult",
    "ConstraintStatus",
    "NamedConstraint",
]
