"""Scan-sharing regression: the reference asserts N scan-shareable
analyzers trigger exactly ONE aggregation job by counting Spark jobs
(SparkMonitor; SURVEY.md §4). The TPU equivalent: count compilations of
the fused update — many analyzers, many batches, ONE trace."""

import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.engine import AnalysisEngine
from fixtures import big_numeric


def test_one_compile_for_many_analyzers_and_batches():
    engine = AnalysisEngine(batch_size=16_384)  # 100k rows -> 7 batches
    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Mean("y"),
        Maximum("y"),
    ]
    context = AnalysisRunner.do_analysis_run(
        big_numeric(), analyzers, engine=engine
    )
    assert all(m.value.is_success for m in context.metric_map.values())
    # ONE fused computation for 9 analyzers over 7 batches
    assert engine.trace_count == 1 or engine.plan_cache_hit


def test_batched_equals_single_batch():
    data = big_numeric()
    analyzers = [Mean("x"), StandardDeviation("x"), Minimum("x"), Sum("y")]
    ctx_one = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine()
    )
    ctx_many = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine(batch_size=4_096)
    )
    for analyzer in analyzers:
        a = ctx_one.metric(analyzer).value.get()
        b = ctx_many.metric(analyzer).value.get()
        assert abs(a - b) < 1e-8 * max(1.0, abs(a)), analyzer


class TestRunMetadata:
    """Per-pass wall-time metadata (SURVEY.md §5.1: an observability
    hook the reference lacks)."""

    def test_runner_records_passes(self):
        import numpy as np

        from deequ_tpu import Dataset, Completeness, Mean, Uniqueness
        from deequ_tpu.analyzers import AnalysisRunner

        ds = Dataset.from_pydict({"x": list(np.arange(1000.0))})
        ctx = AnalysisRunner.do_analysis_run(
            ds, [Completeness("x"), Mean("x"), Uniqueness("x")]
        )
        meta = ctx.run_metadata
        assert meta is not None
        names = [p.name for p in meta.passes]
        # scan-shareable AND grouping analyzers fuse into ONE pass
        assert names == ["scan"]
        for p in meta.passes:
            assert p.wall_s > 0 and p.rows == 1000
        assert meta.passes[0].num_analyzers == 3
        assert meta.total_wall_s > 0
        assert meta.as_records()[0]["pass"] == "scan"

    def test_verification_result_carries_metadata(self):
        import numpy as np

        from deequ_tpu import (
            Check,
            CheckLevel,
            Dataset,
            VerificationSuite,
        )

        ds = Dataset.from_pydict({"x": list(np.arange(100.0))})
        result = (
            VerificationSuite()
            .on_data(ds)
            .add_check(
                Check(CheckLevel.ERROR, "m").has_mean("x", lambda m: m > 0)
            )
            .run()
        )
        assert result.run_metadata is not None
        assert result.run_metadata.passes

    def test_profiler_aggregates_pass_timings(self):
        import numpy as np

        from deequ_tpu import Dataset
        from deequ_tpu.profiles.profiler import ColumnProfiler

        ds = Dataset.from_pydict(
            {"x": list(np.arange(500.0)), "c": ["a", "b"] * 250}
        )
        profiles = ColumnProfiler.profile(ds)
        meta = profiles.run_metadata
        assert meta is not None
        # r4: the string column's histogram rides pass 1 (its small
        # dictionary is known up front), so the WHOLE profile is ONE
        # fused scan — one streamed read of the source
        names = [p.name for p in meta.passes]
        assert names == ["scan"]

        # r5: a bounded-RANGE integer column's histogram ALSO rides
        # pass 1 (the O(1) min/max probe bounds its cardinality), so
        # the whole profile stays one fused scan
        ds2 = Dataset.from_pydict(
            {"x": list(np.arange(500.0)), "k": [1, 2, 3, 4] * 125}
        )
        profiles2 = ColumnProfiler.profile(ds2)
        meta2 = profiles2.run_metadata
        assert [p.name for p in meta2.passes] == ["scan"]
        assert len(profiles2.profiles["k"].histogram.values) == 4
        # a WIDE-range integer that turns out low-cardinality still
        # takes the separate histogram pass (cardinality only known
        # after pass 1)
        ds3 = Dataset.from_pydict(
            {"x": list(np.arange(500.0)),
             "k": [1, 1 << 30, 3, 4] * 125}
        )
        profiles3 = ColumnProfiler.profile(ds3)
        assert [p.name for p in profiles3.run_metadata.passes] == [
            "scan", "scan",
        ]
        assert len(profiles3.profiles["k"].histogram.values) == 4


class TestPlanCache:
    """Cross-run plan reuse must NEVER change results: dataset content
    (values, dictionaries) rides the arguments; dictionary-DEPENDENT
    closures (string predicates) opt out via cache_token=None."""

    def test_cached_plan_correct_across_datasets(self):
        import numpy as np

        from deequ_tpu import (
            ApproxCountDistinct,
            Dataset,
            Histogram,
            Mean,
            PatternMatch,
        )
        from deequ_tpu.analyzers import AnalysisRunner, DataType
        from deequ_tpu.engine import AnalysisEngine

        def make(seed, cats):
            rng = np.random.default_rng(seed)
            return Dataset.from_pydict(
                {
                    "x": list(rng.normal(seed, 1, 5_000)),
                    "s": list(rng.choice(cats, 5_000)),
                }
            )

        analyzers = lambda: [
            Mean("x"),
            ApproxCountDistinct("s"),
            PatternMatch("s", r"@"),
            DataType("s"),
            Histogram("s"),
        ]
        a = make(1, ["u@v", "nope", "x@y", "zz"])
        b = make(2, ["all", "plain", "words"])  # different dictionary!
        e1, e2 = AnalysisEngine(), AnalysisEngine()
        ctx_a = AnalysisRunner.do_analysis_run(a, analyzers(), engine=e1)
        ctx_b = AnalysisRunner.do_analysis_run(b, analyzers(), engine=e2)
        # b's results reflect B's dictionary, not a leaked A LUT
        assert ctx_b.metric(PatternMatch("s", r"@")).value.get() == 0.0
        assert ctx_a.metric(PatternMatch("s", r"@")).value.get() > 0.2
        assert ctx_b.metric(
            ApproxCountDistinct("s")
        ).value.get() == pytest.approx(3, abs=0.5)
        hb = ctx_b.metric(Histogram("s")).value.get()
        assert set(hb.values.keys()) == {"all", "plain", "words"}
        # same plan structure: the second run REUSED the compiled scan
        assert e2.plan_cache_hit

    def test_string_predicates_are_not_cached(self):
        from deequ_tpu import Compliance, Dataset
        from deequ_tpu.analyzers import AnalysisRunner
        from deequ_tpu.engine import AnalysisEngine

        # same expression, different dictionaries -> different code
        # constants in the closure; results must be per-dataset
        a = Dataset.from_pydict({"s": ["hit", "miss", "hit", "miss"]})
        b = Dataset.from_pydict({"s": ["miss", "miss", "hit", "miss"]})
        ca = Compliance("c", "s = 'hit'")
        va = AnalysisRunner.do_analysis_run(a, [ca]).metric(ca).value.get()
        vb = AnalysisRunner.do_analysis_run(b, [ca]).metric(ca).value.get()
        assert va == 0.5 and vb == 0.25

    def test_numeric_predicates_cache_and_stay_correct(self):
        from deequ_tpu import Compliance, Dataset
        from deequ_tpu.analyzers import AnalysisRunner
        from deequ_tpu.engine import AnalysisEngine

        c = Compliance("pos", "x > 0 AND x % 2 = 0")
        a = Dataset.from_pydict({"x": [2.0, -2.0, 4.0, 3.0]})
        b = Dataset.from_pydict({"x": [1.0, 6.0, 8.0, 10.0]})
        e1, e2 = AnalysisEngine(), AnalysisEngine()
        va = AnalysisRunner.do_analysis_run(a, [c], engine=e1).metric(c)
        vb = AnalysisRunner.do_analysis_run(b, [c], engine=e2).metric(c)
        assert va.value.get() == 0.5
        assert vb.value.get() == 0.75
