"""Reference re-cite watch (run at round start).

The reference mount ``/root/reference`` has been EMPTY every round so
far (verified r1-r4), so every ``reference:``/``SURVEY.md`` citation in
this repo is a reconstruction. The day the mount populates, every such
citation must be re-verified against the real files, and the exactness
goldens (tests/goldens/) must be diffed against the real reference's
behavior.

Run: ``python tools/recite_reference.py [--reference PATH]``

- mount empty  -> prints the standing provenance note, exit 0
- mount populated -> prints (a) the reference file inventory, (b) every
  citation in deequ_tpu/**.py + SURVEY.md-derived docs with its source
  location, as a re-verification checklist, and (c) the golden-pack
  diff instructions; exit 1 so a round-start script loudly flags it
"""

from __future__ import annotations

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# "reference:" docstring citations and explicit .scala paths
_CITE = re.compile(
    r"(reference[:\s].{0,120}?\.scala[^\s\)\"`]*|src/main/scala/[^\s\)\"`]+)",
    re.IGNORECASE,
)


def scan_citations():
    out = []
    roots = [
        os.path.join(REPO, "deequ_tpu"),
        os.path.join(REPO, "docs"),
        os.path.join(REPO, "tests"),
    ]
    for root in roots:
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                if not name.endswith((".py", ".md")):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    with open(path, errors="replace") as f:
                        for lineno, line in enumerate(f, 1):
                            for m in _CITE.finditer(line):
                                out.append(
                                    (
                                        os.path.relpath(path, REPO),
                                        lineno,
                                        m.group(0).strip(),
                                    )
                                )
                except OSError:
                    continue
    return out


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--reference", default="/root/reference")
    args = parser.parse_args()

    ref_files = []
    if os.path.isdir(args.reference):
        for dirpath, _dirs, files in os.walk(args.reference):
            for name in files:
                ref_files.append(
                    os.path.relpath(
                        os.path.join(dirpath, name), args.reference
                    )
                )

    if not ref_files:
        print(
            f"reference mount {args.reference} is EMPTY (standing state "
            "since r1): citations remain SURVEY.md reconstructions; "
            "nothing to re-verify this round."
        )
        return 0

    print(
        f"REFERENCE MOUNT POPULATED: {len(ref_files)} files found. "
        "Every citation below must be re-verified against the real "
        "source, and file:line anchors added.\n"
    )
    print("== reference inventory (first 50) ==")
    for f in sorted(ref_files)[:50]:
        print(f"  {f}")
    if len(ref_files) > 50:
        print(f"  ... and {len(ref_files) - 50} more")

    cites = scan_citations()
    print(f"\n== {len(cites)} citations to re-verify ==")
    for path, lineno, text in cites:
        print(f"  {path}:{lineno}: {text}")

    print(
        "\n== exactness goldens ==\n"
        "  Diff tests/goldens/*.json against the real reference's "
        "outputs for the same fixtures (tools/goldens_spec.py defines "
        "them); any mismatch is a semantic divergence to fix or "
        "document. Then regenerate deliberately via "
        "tools/make_goldens.py."
    )
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
