"""Packed host<->device state transfer.

On tunneled TPU chips every per-leaf ``device_get`` is a sequential
host<->device round trip (~5-10ms each); fetching a 125-analyzer plan's
~250 state leaves one by one costs seconds while the actual payload is a
few kilobytes. The fix: the traced epilogue concatenates every state
leaf into ONE 1-D array per dtype (``pack_tree``), the host fetches that
handful of arrays in one ``device_get``, and ``unpack_tree`` slices the
flat buffers back into the original pytree using a host-side template —
the template is always known (init states are host numpy; lax.scan
carries preserve shape/dtype exactly).

Reference analog: none — Spark collects one aggregated Row per job
(SURVEY.md §3.1 ★#1); this restores that "one result row" property on
the tunnel.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceResident:
    """Marker wrapper for a pytree leaf ``packed_device_get`` must NOT
    fetch. The fused scan wraps collector op states (device-resident
    spill key buffers, megabytes of u64 keys) in this before the
    epilogue fetch: the wrapper is not registered as a pytree node, so
    it flattens as an opaque leaf and — not being a ``jax.Array`` —
    passes through the packed transfer untouched. The buffers stay in
    device memory for the post-scan sort finalize (analyzers/spill.py)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


def _canonical_dtype_name(dtype) -> str:
    return np.dtype(jax.dtypes.canonicalize_dtype(dtype)).name


def _shape_dtype(leaf) -> Tuple[Tuple[int, ...], Any]:
    """(shape, dtype) without materializing device values."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        arr = np.asarray(leaf)  # plain Python scalar/sequence: host-side
        shape, dtype = arr.shape, arr.dtype
    return tuple(shape), dtype


def pack_tree(tree: Any) -> Dict[str, jnp.ndarray]:
    """Traced: concatenate all leaves into one 1-D array per dtype.

    Leaves are raveled and concatenated in ``tree_leaves`` order, so the
    host can slice them back out against any structurally-equal template.
    """
    groups: Dict[str, list] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = jnp.asarray(leaf)
        groups.setdefault(_canonical_dtype_name(arr.dtype), []).append(
            arr.ravel()
        )
    return {
        name: parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        for name, parts in groups.items()
    }


def unpack_tree(packed: Dict[str, np.ndarray], template: Any) -> Any:
    """Host: slice the fetched flat buffers back into ``template``'s
    structure. ``template`` leaves only need ``.shape``/``.dtype``
    (numpy arrays, scalars, or ``jax.ShapeDtypeStruct`` all work)."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    offsets = {name: 0 for name in packed}
    out = []
    for leaf in leaves:
        shape, dtype = _shape_dtype(leaf)
        name = _canonical_dtype_name(dtype)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        off = offsets[name]
        flat = np.asarray(packed[name][off:off + size])
        offsets[name] = off + size
        out.append(flat.reshape(shape) if shape else flat.reshape(())[()])
    return jax.tree_util.tree_unflatten(treedef, out)


def scan_output_template(
    init_states: Tuple[Any, ...], host_slots, nb: int
) -> Tuple[Any, Any]:
    """Shape/dtype template for the fused scan's packed output
    ``(final_states, ys)``: final states mirror the init states (scan
    carries preserve shape/dtype); each host-slot y is that op's state
    with a leading ``nb`` (stacked per-batch outputs)."""

    def struct(leaf, lead: Tuple[int, ...] = ()):
        # shape/dtype attributes only — np.asarray on a DEVICE leaf
        # would fetch its value (a tunnel round trip per leaf, the very
        # cost this module exists to remove)
        shape, dtype = _shape_dtype(leaf)
        return jax.ShapeDtypeStruct(
            lead + shape, jax.dtypes.canonicalize_dtype(dtype)
        )

    finals = jax.tree_util.tree_map(struct, init_states)
    ys = tuple(
        jax.tree_util.tree_map(lambda l: struct(l, (nb,)), init_states[i])
        for i in host_slots
    )
    return finals, ys


def packed_device_get(tree: Any) -> Any:
    """Fetch an arbitrary device pytree in one transfer per dtype.

    Generic helper for paths that don't fold the pack into their own
    jitted program. Runs EAGERLY (ravel + concatenate dispatches, no
    jit): a jitted pack would recompile for every distinct leaf count —
    e.g. a streaming run's pending host-fold outputs scale with the
    batch count. Host-side leaves (numpy, Python scalars) and
    :class:`DeviceResident`-wrapped leaves pass through untouched; only
    bare ``jax.Array`` leaves are packed and fetched."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    device_idx = [
        i for i, leaf in enumerate(leaves) if isinstance(leaf, jax.Array)
    ]
    if not device_idx:
        return tree
    from deequ_tpu.telemetry import get_telemetry

    get_telemetry().counter("engine.device_fetches").inc()
    groups: Dict[str, list] = {}
    group_members: Dict[str, list] = {}
    for i in device_idx:
        name = _canonical_dtype_name(leaves[i].dtype)
        groups.setdefault(name, []).append(jnp.ravel(leaves[i]))
        group_members.setdefault(name, []).append(i)
    packed = jax.device_get(
        {
            name: parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            for name, parts in groups.items()
        }
    )
    # bytes actually pulled over the link per sync — together with
    # engine.device_fetches this is the sync-discipline audit surface
    # (tests/test_sync_discipline.py pins fetches; dashboards trend
    # bytes/fetch to catch a state blow-up before it costs seconds)
    get_telemetry().counter("engine.fetch_bytes").inc(
        # lint-ok: trace-hazard: post-device_get accounting — `packed`
        # is host numpy here; this IS the sanctioned sync epilogue
        int(sum(np.asarray(a).nbytes for a in packed.values()))
    )
    out = list(leaves)
    for name, members in group_members.items():
        off = 0
        flat = packed[name]
        for i in members:
            shape = tuple(leaves[i].shape)
            # lint-ok: trace-hazard: static shape arithmetic on the
            # host side of the epilogue
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            # lint-ok: trace-hazard: slicing the already-fetched host
            # buffer back into per-leaf views
            piece = np.asarray(flat[off:off + size])
            off += size
            out[i] = piece.reshape(shape) if shape else piece.reshape(())[()]
    return jax.tree_util.tree_unflatten(treedef, out)
