"""CustomSql: a metric from an arbitrary scalar aggregate expression.

Reference: ``analyzers/CustomSql.scala`` (SURVEY.md §2.2, newer
upstream): run arbitrary SQL returning one double. The reference hands
the statement to Spark SQL; here the expression compiles onto the fused
scan: every aggregate call (SUM/COUNT/AVG/MIN/MAX, COUNT(*)) becomes a
slot in a mergeable state updated in the shared pass, and the
surrounding arithmetic evaluates host-side over the final scalars. So
``CustomSql("SUM(a) / SUM(b) + 1")`` costs the same single data pass as
every other scan-shareable analyzer, and its state merges across
batches/mesh/persisted increments like any other monoid.

State layout: one universal aggregate cell per slot, stored as four
parallel vectors (sums f64[k], counts i64[k], mins f64[k], maxs f64[k])
— a fixed-shape pytree with a slot-count-independent elementwise merge,
so the incremental path can merge persisted states without knowing the
expression.

Supported grammar: the predicate expression language (deequ_tpu.sql)
with aggregate calls over a single column (or ``*`` for COUNT) combined
with +, -, *, /, %, and numeric literals. WHERE-style filtering uses the
analyzer's ``where`` parameter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import (
    EmptyStateException,
    IllegalAnalyzerParameterException,
    Precondition,
    ScanOps,
    ScanShareableAnalyzer,
    has_column,
    is_numeric,
)
from deequ_tpu.analyzers.basic import (
    _col_mask,
    _compile_where,
    _mcount,
    _mmax,
    _mmin,
    _msum,
    _row_mask,
)
from deequ_tpu.data.table import ColumnRequest, Dataset
from deequ_tpu.metrics.metric import DoubleMetric, Entity
from deequ_tpu.sql.predicate import (
    BinOp,
    ColumnRef,
    FuncCall,
    Node,
    NumberLit,
    PredicateParseError,
    StarLit,
    UnaryOp,
    parse_predicate,
)

_AGGREGATES = ("SUM", "COUNT", "AVG", "MIN", "MAX")

# aggregate slot: (function name, column name or "*")
_Slot = Tuple[str, str]


class CustomSqlState(NamedTuple):
    """k universal aggregate cells as parallel vectors; merge is
    elementwise and expression-independent."""

    sums: jnp.ndarray  # f64[k]
    counts: jnp.ndarray  # i64[k]
    mins: jnp.ndarray  # f64[k]
    maxs: jnp.ndarray  # f64[k]

    @staticmethod
    def identity(k: int) -> "CustomSqlState":
        # min identity is NaN, not +inf: under the Spark ordering
        # (NaN largest) NaN is the true identity of nan_largest_min —
        # +inf would beat an all-NaN batch's NaN and surface as a
        # bogus MIN() = inf (states.MinState has the same identity)
        return CustomSqlState(
            np.zeros(k, dtype=np.float64),
            np.zeros(k, dtype=np.int64),
            np.full(k, np.nan, dtype=np.float64),
            np.full(k, -np.inf, dtype=np.float64),
        )

    @staticmethod
    def merge(a: "CustomSqlState", b: "CustomSqlState") -> "CustomSqlState":
        from deequ_tpu.analyzers.states import nan_largest_min

        return CustomSqlState(
            a.sums + b.sums,
            a.counts + b.counts,
            nan_largest_min(a.mins, b.mins),
            jnp.maximum(a.maxs, b.maxs),
        )


def _collect_aggregates(node: Node, out: List[_Slot]) -> None:
    """Walk the AST collecting aggregate calls; validate that column
    references appear ONLY inside aggregates (a bare column has no
    scalar meaning in an aggregate expression)."""
    if isinstance(node, FuncCall) and node.name in _AGGREGATES:
        if len(node.args) != 1:
            raise PredicateParseError(
                f"{node.name} takes exactly one argument"
            )
        arg = node.args[0]
        if isinstance(arg, StarLit):
            if node.name != "COUNT":
                raise PredicateParseError(
                    f"* is only valid in COUNT(*), not {node.name}"
                )
            slot = (node.name, "*")
        elif isinstance(arg, ColumnRef):
            slot = (node.name, arg.name)
        else:
            raise PredicateParseError(
                f"{node.name} expects a column (or * for COUNT)"
            )
        if slot not in out:
            out.append(slot)
        return
    if isinstance(node, ColumnRef):
        raise PredicateParseError(
            f"bare column {node.name!r} outside an aggregate — aggregate "
            "expressions reduce to one scalar"
        )
    if isinstance(node, NumberLit):
        return
    if isinstance(node, UnaryOp) and node.op == "NEG":
        _collect_aggregates(node.operand, out)
        return
    if isinstance(node, BinOp) and node.op in ("+", "-", "*", "/", "%"):
        _collect_aggregates(node.left, out)
        _collect_aggregates(node.right, out)
        return
    raise PredicateParseError(
        f"unsupported node in aggregate expression: {node!r}"
    )


def _finalize(node: Node, values: Dict[_Slot, float]) -> float:
    """Host-side arithmetic over the final aggregate scalars."""
    if isinstance(node, FuncCall) and node.name in _AGGREGATES:
        arg = node.args[0]
        col = "*" if isinstance(arg, StarLit) else arg.name  # type: ignore[union-attr]
        return values[(node.name, col)]
    if isinstance(node, NumberLit):
        return node.value
    if isinstance(node, UnaryOp):
        return -_finalize(node.operand, values)
    if isinstance(node, BinOp):
        left = _finalize(node.left, values)
        right = _finalize(node.right, values)
        if node.op == "+":
            return left + right
        if node.op == "-":
            return left - right
        if node.op == "*":
            return left * right
        if node.op == "/":
            if right == 0:
                raise IllegalAnalyzerParameterException(
                    "division by zero in CustomSql expression"
                )
            return left / right
        if node.op == "%":
            if right == 0:
                raise IllegalAnalyzerParameterException(
                    "modulo by zero in CustomSql expression"
                )
            return left % right
    raise PredicateParseError(f"cannot finalize node {node!r}")


# persisted-state serde registration (state_provider resolves by name)
from deequ_tpu.analyzers.states import STATE_TYPES  # noqa: E402

STATE_TYPES.setdefault("CustomSqlState", CustomSqlState)


@dataclass(frozen=True)
class CustomSql(ScanShareableAnalyzer):
    expression: str
    where: Optional[str] = None

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    @property
    def instance(self) -> str:
        return self.expression

    def _plan(self) -> Tuple[Node, List[_Slot]]:
        node = parse_predicate(self.expression)
        slots: List[_Slot] = []
        _collect_aggregates(node, slots)
        if not slots:
            raise PredicateParseError(
                "aggregate expression contains no aggregate call"
            )
        return node, slots

    def preconditions(self) -> List[Precondition]:
        try:
            _, slots = self._plan()
        except PredicateParseError:
            # surface the parse error as a failure metric at run time
            def bad(schema):
                self._plan()

            return [bad]
        checks: List[Precondition] = []
        for func, col in slots:
            if col == "*":
                continue
            checks.append(has_column(col))
            if func in ("SUM", "AVG", "MIN", "MAX"):
                checks.append(is_numeric(col))
        return checks

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, slots = self._plan()
        _, where_reqs = _compile_where(self.where, dataset)
        requests: List[ColumnRequest] = list(where_reqs)
        for _, col in slots:
            if col == "*":
                continue
            requests.append(ColumnRequest(col, "values"))
            requests.append(ColumnRequest(col, "mask"))
        return requests

    def make_ops(self, dataset: Dataset) -> ScanOps:
        _, slots = self._plan()
        where_fn, _ = _compile_where(self.where, dataset)
        k = len(slots)

        def update(state: CustomSqlState, batch) -> CustomSqlState:
            sums, counts, mins, maxs = [], [], [], []
            for func, col in slots:
                if col == "*":
                    mask = _row_mask(batch, where_fn)
                    sums.append(jnp.float64(0.0))
                    counts.append(_mcount(mask))
                    mins.append(jnp.float64(jnp.inf))
                    maxs.append(jnp.float64(-jnp.inf))
                    continue
                mask = _col_mask(batch, col, where_fn)
                values = batch[f"{col}::values"]
                need_sum = func in ("SUM", "AVG")
                need_ends = func in ("MIN", "MAX")
                sums.append(
                    _msum(values, mask).astype(jnp.float64)
                    if need_sum
                    else jnp.float64(0.0)
                )
                counts.append(_mcount(mask))
                mins.append(
                    _mmin(values, mask) if need_ends else jnp.float64(jnp.inf)
                )
                maxs.append(
                    _mmax(values, mask)
                    if need_ends
                    else jnp.float64(-jnp.inf)
                )
            batch_state = CustomSqlState(
                jnp.stack(sums), jnp.stack(counts),
                jnp.stack(mins), jnp.stack(maxs),
            )
            return CustomSqlState.merge(state, batch_state)

        return ScanOps(
            lambda: CustomSqlState.identity(k),
            update,
            CustomSqlState.merge,
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer CustomSql.")
            )
        node, slots = self._plan()
        values: Dict[_Slot, float] = {}
        for i, (func, col) in enumerate(slots):
            count = int(np.asarray(state.counts)[i])
            if func == "SUM":
                values[(func, col)] = float(np.asarray(state.sums)[i])
            elif func == "COUNT":
                values[(func, col)] = float(count)
            elif func == "AVG":
                if count == 0:
                    return self.to_failure_metric(
                        EmptyStateException(
                            f"AVG({col}) over zero rows in CustomSql."
                        )
                    )
                values[(func, col)] = float(np.asarray(state.sums)[i]) / count
            elif func == "MIN":
                if count == 0:
                    return self.to_failure_metric(
                        EmptyStateException(
                            f"MIN({col}) over zero rows in CustomSql."
                        )
                    )
                # -0.0 -> 0.0: same normalization as Minimum (backend-
                # independent; basic.py documents why)
                values[(func, col)] = (
                    float(np.asarray(state.mins)[i]) + 0.0
                )
            else:  # MAX
                if count == 0:
                    return self.to_failure_metric(
                        EmptyStateException(
                            f"MAX({col}) over zero rows in CustomSql."
                        )
                    )
                values[(func, col)] = (
                    float(np.asarray(state.maxs)[i]) + 0.0
                )
        try:
            result = _finalize(node, values)
        except Exception as exc:  # noqa: BLE001
            return self.to_failure_metric(exc)
        return DoubleMetric.success(
            self.entity, "CustomSql", self.instance, float(result)
        )
