"""Elastic device placement: bin-pack concurrent runs onto mesh
sub-slices (docs/SERVICE.md "Elastic placement").

The coalescer (service/coalesce.py) fuses COMPATIBLE runs into one
superset scan; any two runs that cannot coalesce still serialized on
the whole device mesh — a fleet of small interactive suites left most
chips idle while one large run monopolized all of them. This module
packs concurrent runs onto DISJOINT device sub-slices instead:

- :class:`DevicePool` — tracks which devices are free. Slices are
  power-of-two sized and buddy-ALIGNED (a k-device slice starts at an
  offset divisible by k), so released slices re-merge into larger free
  blocks instead of fragmenting the pool: two 1-device runs can never
  straddle an aligned 2-device block and starve a 2-device run that
  would otherwise fit.
- :class:`PlacementPolicy` — picks the slice size (1/2/4/8...) for a
  run from its estimated device footprint
  (``engine.scan.estimated_run_bytes``, the same coarse estimate the
  admission watermark gates on): ``ceil(estimated_bytes /
  bytes_per_device)`` rounded up to a power of two, clamped to the
  pool. Runs with no estimate get ``default_devices``.
- :class:`MeshCache` — LRU of ``jax.sharding.Mesh`` objects per chosen
  device subset. Reusing the SAME ``Mesh`` object for the same slice
  keeps jit signatures equal across runs, so a warmed per-shape plan
  (engine/scan.py ``_placement_shape``) re-executes with zero traces.
- :class:`ElasticPlacer` — the facade the scheduler drives: ``place()``
  blocks until a slice frees up (lease wait counts as queue wait — the
  handle's ``started_at`` is stamped AFTER placement, and the run's
  deadline budget burns while it waits, mirroring the admission
  controller's queued-run semantics), returns a
  :class:`PlacementLease`; ``release()`` returns the slice to the pool.

Per-token shape affinity: once a structural hint (dataset key + plan
surface) has run on a slice shape, later runs with the same hint
prefer that shape — the per-shape plan cache already holds their
compiled program, so a pool-pressure-driven resize never eats a fresh
compile in steady state.

Thread discipline: this module runs on the service's INJECTED clock
(``MonotonicClock``/``ManualClock``) only, constructs no threads, and
never references the engine's scan entry points — the lease carries a
``Mesh``; the service's executor hands it to ``AnalysisEngine`` and
still enters the engine through the runner's admission layer.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.engine.deadline import (
    DeadlineExceeded,
    MonotonicClock,
    RunCancelled,
)
from deequ_tpu.telemetry import get_telemetry

#: service.placement_wait_s histogram buckets — same shape as the
#: scheduler's queue-wait buckets (lease wait IS queue wait)
PLACEMENT_WAIT_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0)


def _floor_pow2(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _ceil_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    p = 1
    while p < n:
        p *= 2
    return p


@dataclass
class PlacementLease:
    """One granted device slice: the concrete devices, their pool
    offset, and the (LRU-cached) ``Mesh`` built over them. Owned by the
    scheduler for the run's duration; ``ElasticPlacer.release`` is the
    only way back to the pool."""

    devices: Tuple[Any, ...]
    start: int
    ndev: int
    mesh: Any
    wait_s: float = 0.0
    released: bool = False

    @property
    def device_ids(self) -> List[int]:
        return [
            int(getattr(d, "id", i)) for i, d in enumerate(self.devices)
        ]


class DevicePool:
    """Free-set tracker over an ordered device list with buddy-aligned
    power-of-two slice allocation.

    ``acquire`` blocks until an aligned run of ``ndev`` free devices
    exists, polling at the injected clock's cadence so a waiting run's
    own deadline budget (possibly on a fake clock) and cancel tokens
    stay live — the same contract as
    :class:`~deequ_tpu.engine.deadline.AdmissionController`. A lease
    that cannot be granted before EVERY live budget expires raises
    :class:`DeadlineExceeded` (a run that cannot start in time must not
    start); one whose every cancel token fired raises
    :class:`RunCancelled`."""

    def __init__(self, devices: Optional[Sequence[Any]] = None, clock=None):
        if devices is None:
            import jax

            devices = list(jax.devices())
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        self._devices: List[Any] = list(devices)
        self._busy = [False] * len(self._devices)
        self._cond = threading.Condition()
        self._clock = clock or MonotonicClock()

    @property
    def total(self) -> int:
        return len(self._devices)

    @property
    def max_slice(self) -> int:
        """Largest grantable slice (the pool's floor power of two)."""
        return _floor_pow2(len(self._devices))

    def free_count(self) -> int:
        with self._cond:
            return sum(1 for b in self._busy if not b)

    def busy_map(self) -> List[bool]:
        with self._cond:
            return list(self._busy)

    def _find_slot_locked(self, ndev: int) -> Optional[int]:
        n = len(self._busy)
        for start in range(0, n - ndev + 1, ndev):  # buddy alignment
            if not any(self._busy[start:start + ndev]):
                return start
        return None

    def try_acquire(self, ndev: int) -> Optional[Tuple[int, Tuple[Any, ...]]]:
        """Non-blocking grant of an aligned ``ndev`` slice, or None."""
        ndev = self._clamp(ndev)
        with self._cond:
            start = self._find_slot_locked(ndev)
            if start is None:
                return None
            for i in range(start, start + ndev):
                self._busy[i] = True
            return start, tuple(self._devices[start:start + ndev])

    def _clamp(self, ndev: int) -> int:
        return max(1, min(_ceil_pow2(max(1, int(ndev))), self.max_slice))

    def acquire(
        self,
        ndev: int,
        budgets: Sequence[Any] = (),
        cancels: Sequence[Any] = (),
    ) -> Tuple[int, Tuple[Any, ...]]:
        """Block until an aligned ``ndev`` slice frees up. Returns
        ``(start, devices)``. Deadline/cancel semantics documented on
        the class."""
        ndev = self._clamp(ndev)
        live_budgets = [b for b in budgets if b is not None]
        live_cancels = [c for c in cancels if c is not None]
        for budget in live_budgets:
            budget.start()  # idempotent: already started at submit
        with self._cond:
            while True:
                start = self._find_slot_locked(ndev)
                if start is not None:
                    for i in range(start, start + ndev):
                        self._busy[i] = True
                    return start, tuple(
                        self._devices[start:start + ndev]
                    )
                # a group shares one lease wait: interrupt only once
                # EVERY member's envelope is closed, so the surviving
                # members still get their (possibly partial) results
                if live_cancels and all(
                    c.cancelled for c in live_cancels
                ):
                    raise RunCancelled(
                        "cancelled while waiting for a device slice"
                    )
                if live_budgets and all(
                    b.expired() for b in live_budgets
                ):
                    raise DeadlineExceeded(
                        "waited for a device slice past the run "
                        "deadline"
                    )
                self._cond.wait(timeout=self._clock.queue_poll_s())

    def release(self, start: int, ndev: int) -> None:
        with self._cond:
            for i in range(start, start + ndev):
                self._busy[i] = False
            self._cond.notify_all()


@dataclass(frozen=True)
class PlacementPolicy:
    """Slice-size policy: one device per ``bytes_per_device`` of the
    run's estimated footprint, rounded UP to a power of two, clamped to
    ``[1, min(max_devices, pool)]``. Runs with no estimate (factory
    datasets whose size is unknown at submit) get ``default_devices``.
    The policy table lives in docs/SERVICE.md "Elastic placement"."""

    bytes_per_device: int = 512 << 20
    max_devices: int = 0  # 0 = the whole pool
    default_devices: int = 1

    def slice_size(self, estimated_bytes: int, pool_max: int) -> int:
        cap = pool_max
        if self.max_devices > 0:
            cap = min(cap, _floor_pow2(self.max_devices))
        cap = max(1, cap)
        if estimated_bytes <= 0:
            want = max(1, int(self.default_devices))
        else:
            per = max(1, int(self.bytes_per_device))
            want = -(-int(estimated_bytes) // per)
        return max(1, min(_ceil_pow2(want), cap))


class MeshCache:
    """LRU of ``jax.sharding.Mesh`` objects keyed by the device-id
    tuple of the slice. Object identity matters beyond the build cost:
    handing runs the SAME ``Mesh`` for the same slice keeps their input
    shardings equal, so jit serves the cached executable instead of
    re-tracing (the per-shape warm contract)."""

    def __init__(self, cap: int = 8, axis: str = "dp"):
        self.cap = max(1, int(cap))
        self.axis = axis
        self._lock = threading.Lock()
        self._meshes: "OrderedDict[tuple, Any]" = OrderedDict()

    def mesh_for(self, devices: Sequence[Any]):
        import numpy as np
        from jax.sharding import Mesh

        key = tuple(
            int(getattr(d, "id", i)) for i, d in enumerate(devices)
        )
        with self._lock:
            mesh = self._meshes.get(key)
            if mesh is not None:
                self._meshes.move_to_end(key)
                return mesh
        mesh = Mesh(np.array(list(devices)), (self.axis,))
        with self._lock:
            existing = self._meshes.get(key)
            if existing is not None:
                self._meshes.move_to_end(key)
                return existing
            self._meshes[key] = mesh
            while len(self._meshes) > self.cap:
                self._meshes.popitem(last=False)
        return mesh

    def __len__(self) -> int:
        with self._lock:
            return len(self._meshes)


class ElasticPlacer:
    """Pool + policy + mesh cache behind one ``place``/``release``
    pair. Telemetry: ``service.placements`` counter,
    ``service.placement_wait_s`` histogram, ``service.slices_active``
    gauge, and one ``run_placed`` event per placed run (run id, slice
    size, device ids, lease wait)."""

    def __init__(
        self,
        pool: Optional[DevicePool] = None,
        policy: Optional[PlacementPolicy] = None,
        clock=None,
        mesh_cache_slices: Optional[int] = None,
    ):
        from deequ_tpu import config

        opts = config.options()
        self.clock = clock or MonotonicClock()
        self.pool = pool or DevicePool(clock=self.clock)
        self.policy = policy or PlacementPolicy(
            bytes_per_device=opts.service_placement_bytes_per_device,
            max_devices=opts.service_placement_max_devices,
            default_devices=opts.service_placement_default_devices,
        )
        self.meshes = MeshCache(
            cap=(
                opts.service_placement_mesh_cache_slices
                if mesh_cache_slices is None
                else mesh_cache_slices
            )
        )
        self._lock = threading.Lock()
        self._active_slices = 0
        # structural hint -> slice shape last granted for it (the
        # per-shape plan cache already holds that shape's program)
        self._shape_affinity: Dict[Any, int] = {}

    # -- sizing ---------------------------------------------------------

    def slice_for(
        self, estimated_bytes: int, hint: Any = None
    ) -> int:
        with self._lock:
            preferred = (
                self._shape_affinity.get(hint) if hint is not None else None
            )
        if preferred is not None:
            return min(preferred, self.pool.max_slice)
        return self.policy.slice_size(
            estimated_bytes, self.pool.max_slice
        )

    # -- lease lifecycle -------------------------------------------------

    def place(
        self,
        estimated_bytes: int = 0,
        hint: Any = None,
        run_ids: Sequence[str] = (),
        budgets: Sequence[Any] = (),
        cancels: Sequence[Any] = (),
    ) -> PlacementLease:
        """Grant a slice for one run (or one coalesced group — the
        whole group shares a single lease). Blocks until the pool can
        serve it; the wait shows up in the run's queue-wait histogram
        because ``started_at`` is stamped after placement."""
        tm = get_telemetry()
        ndev = self.slice_for(estimated_bytes, hint=hint)
        t0 = self.clock.now()
        start, devices = self.pool.acquire(
            ndev, budgets=budgets, cancels=cancels
        )
        wait_s = max(0.0, self.clock.now() - t0)
        mesh = self.meshes.mesh_for(devices)
        lease = PlacementLease(
            devices=devices,
            start=start,
            ndev=len(devices),
            mesh=mesh,
            wait_s=wait_s,
        )
        with self._lock:
            if hint is not None:
                self._shape_affinity[hint] = lease.ndev
                # bounded: affinity is a hot-set memo, not a registry
                while len(self._shape_affinity) > 256:
                    self._shape_affinity.pop(
                        next(iter(self._shape_affinity))
                    )
            self._active_slices += 1
            active = self._active_slices
        tm.counter("service.placements").inc()
        tm.metrics.histogram(
            "service.placement_wait_s", buckets=PLACEMENT_WAIT_BUCKETS
        ).observe(wait_s)
        tm.metrics.gauge("service.slices_active").set(active)
        for run_id in run_ids or ("?",):
            tm.event(
                "run_placed",
                run_id=run_id,
                ndev=lease.ndev,
                device_ids=",".join(str(i) for i in lease.device_ids),
                lease_wait_s=round(wait_s, 6),
            )
        return lease

    def release(self, lease: PlacementLease) -> None:
        with self._lock:
            if lease.released:
                return
            lease.released = True
            self._active_slices = max(0, self._active_slices - 1)
            active = self._active_slices
        self.pool.release(lease.start, lease.ndev)
        get_telemetry().metrics.gauge("service.slices_active").set(
            active
        )

    def revoke(
        self,
        lease: PlacementLease,
        run_ids: Any = (),
        reason: str = "preempted",
    ) -> None:
        """Reclaim a PREEMPTED group's slice (docs/SERVICE.md
        "Preemption and autoscaling"): the same idempotent return to
        the pool as ``release``, accounted separately so the
        observability plane can tell a slice freed by completion from
        one taken back under interactive pressure. The scheduler only
        reaches this after extracting checkpoint evidence for the
        victim (``preempt_checkpoint_evidence``; the staticcheck
        ``preempt-discipline`` rule pins that ordering), so a revoked
        lease never strands un-checkpointed work."""
        if lease.released:
            return
        tm = get_telemetry()
        tm.counter("service.lease_revocations").inc()
        tm.event(
            "service_lease_revoked",
            ndev=lease.ndev,
            device_ids=list(getattr(lease, "device_ids", ()) or ()),
            run_ids=list(run_ids),
            reason=reason,
        )
        self.release(lease)

    # -- introspection ---------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            active = self._active_slices
            affinity = dict(self._shape_affinity)
        return {
            "pool_total": self.pool.total,
            "pool_free": self.pool.free_count(),
            "active_slices": active,
            "cached_meshes": len(self.meshes),
            "shape_affinity": {
                str(k): v for k, v in affinity.items()
            },
        }
