"""CustomSql aggregate expressions, Applicability checking, and
row-level results (SURVEY.md §2.2 CustomSql, §1 L12 applicability,
§2.2 rowLevelResultsAsDataFrame)."""

import numpy as np
import pytest

from deequ_tpu import (
    Applicability,
    Check,
    CheckLevel,
    Compliance,
    CustomSql,
    Dataset,
    Mean,
    PatternMatch,
    Size,
    Uniqueness,
    VerificationSuite,
)
from deequ_tpu.analyzers import AnalysisRunner


def value(analyzer, ds):
    return analyzer.calculate(ds).value.get()


class TestCustomSql:
    @pytest.fixture(scope="class")
    def ds(self):
        return Dataset.from_pydict(
            {
                "a": [1.0, 2.0, 3.0, 4.0],
                "b": [2.0, 2.0, 2.0, None],
                "s": ["x", "y", "x", "z"],
            }
        )

    def test_basic_aggregates(self, ds):
        assert value(CustomSql("SUM(a)"), ds) == 10.0
        assert value(CustomSql("COUNT(*)"), ds) == 4.0
        assert value(CustomSql("COUNT(b)"), ds) == 3.0  # nulls skipped
        assert value(CustomSql("AVG(a)"), ds) == 2.5
        assert value(CustomSql("MIN(a)"), ds) == 1.0
        assert value(CustomSql("MAX(a)"), ds) == 4.0

    def test_arithmetic_composition(self, ds):
        assert value(CustomSql("SUM(a) / SUM(b)"), ds) == pytest.approx(
            10.0 / 6.0
        )
        assert value(
            CustomSql("AVG(a) * 2 + MIN(a) - 1"), ds
        ) == pytest.approx(5.0)
        assert value(CustomSql("SUM(a) / COUNT(*)"), ds) == 2.5

    def test_where_filter(self, ds):
        assert value(CustomSql("SUM(a)", where="a > 2"), ds) == 7.0
        assert value(CustomSql("COUNT(*)", where="a > 2"), ds) == 2.0

    def test_incremental_merge(self, ds):
        """The state merges monoidally like every other analyzer."""
        a = CustomSql("SUM(a) / COUNT(*)")
        ops = a.make_ops(ds)
        half1 = Dataset.from_pydict({"a": [1.0, 2.0], "b": [1.0, 1.0]})
        half2 = Dataset.from_pydict({"a": [3.0, 4.0], "b": [1.0, 1.0]})
        s1 = AnalysisRunner.do_analysis_run(half1, [a]).metric(a)
        # merge states through the engine path
        from deequ_tpu.engine import AnalysisEngine

        engine = AnalysisEngine()
        st1 = engine.run_scan(half1, [(a, a.make_ops(half1))])[0]
        st2 = engine.run_scan(half2, [(a, a.make_ops(half2))])[0]
        merged = type(st1).merge(st1, st2)
        assert a.compute_metric_from_state(merged).value.get() == 2.5

    def test_failure_modes(self, ds):
        assert CustomSql("SUM(nope)").calculate(ds).value.is_failure
        assert CustomSql("a + 1").calculate(ds).value.is_failure  # bare col
        assert CustomSql("SUM(s)").calculate(ds).value.is_failure  # string
        assert CustomSql("1 + 2").calculate(ds).value.is_failure  # no agg
        assert CustomSql(
            "SUM(a) / SUM(a) - SUM(a) / SUM(a) + SUM(a) / (SUM(a) - SUM(a))"
        ).calculate(ds).value.is_failure  # div by zero

    def test_shares_the_fused_scan(self, ds):
        from deequ_tpu.engine import AnalysisEngine

        engine = AnalysisEngine()
        ctx = AnalysisRunner.do_analysis_run(
            ds, [CustomSql("SUM(a)"), Mean("a"), Size()], engine=engine
        )
        assert engine.trace_count == 1 or engine.plan_cache_hit
        assert ctx.metric(CustomSql("SUM(a)")).value.get() == 10.0


class TestApplicability:
    def test_check_applicability(self):
        ds = Dataset.from_pydict({"x": [1.0], "s": ["a"]})
        schema = ds.schema
        good = (
            Check(CheckLevel.ERROR, "good")
            .is_complete("x")
            .has_mean("x", lambda m: m > 0)
        )
        result = Applicability().is_applicable(good, schema)
        assert result.is_applicable
        bad = Check(CheckLevel.ERROR, "bad").has_mean("s", lambda m: m > 0)
        result = Applicability().is_applicable(bad, schema)
        assert not result.is_applicable
        assert any(v is not None for v in result.failures.values())

    def test_analyzer_applicability(self):
        ds = Dataset.from_pydict({"x": [1.0]})
        result = Applicability().are_applicable(
            [Mean("x"), Mean("missing")], ds.schema
        )
        assert not result.is_applicable
        assert result.failures[repr(Mean("x"))] is None
        assert result.failures[repr(Mean("missing"))] is not None


class TestRowLevelResults:
    def test_row_level_outcomes(self):
        ds = Dataset.from_pydict(
            {
                "x": [1.0, -2.0, 3.0, None],
                "id": [1, 2, 2, 4],
                "email": ["a@b.com", "nope", "c@d.org", None],
            }
        )
        check = (
            Check(CheckLevel.ERROR, "rl")
            .is_complete("x")
            .satisfies("x > 0", "positive", lambda v: v == 1.0)
            .is_unique("id")
            .contains_email("email", lambda v: v == 1.0)
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        assert rl.num_rows == 4
        by_name = {
            name: rl.column(name).to_pylist() for name in rl.schema.names
        }
        completeness = next(
            v for k, v in by_name.items() if "Completeness" in k
        )
        assert completeness == [True, True, True, False]
        positive = next(v for k, v in by_name.items() if "positive" in k)
        assert positive == [True, False, True, False]
        unique = next(v for k, v in by_name.items() if "Uniqueness" in k)
        assert unique == [True, False, False, True]
        email = next(v for k, v in by_name.items() if "email" in k.lower())
        assert email == [True, False, True, False]

    def test_uniqueness_row_level_respects_where(self):
        """Occurrence counts for row-level Uniqueness/UniqueValueRatio
        run over the FILTERED data: a key unique within the filter
        passes even when a where-excluded row shares it (r5 review
        finding)."""
        ds = Dataset.from_pydict({"id": [1, 1, 2], "g": [1, 2, 1]})
        check = (
            Check(CheckLevel.ERROR, "w")
            .has_unique_value_ratio(["id"], lambda v: v == 1.0)
            .where("g = 1")
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        col = rl.column(rl.schema.names[0]).to_pylist()
        # row 0: only id=1 INSIDE the filter -> unique -> passes;
        # row 1: excluded -> passes by default; row 2: unique
        assert col == [True, True, True]

    def test_unique_value_ratio_row_level(self):
        """UniqueValueRatio marks exactly the rows whose key occurs
        once — the reference's RowLevelGroupedConstraint rule, same as
        Uniqueness (r5)."""
        ds = Dataset.from_pydict({"id": [1, 2, 2, 3, 3, 4]})
        check = Check(CheckLevel.ERROR, "uvr").has_unique_value_ratio(
            ["id"], lambda v: v >= 0.5
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        col = rl.column(rl.schema.names[0]).to_pylist()
        assert col == [True, False, False, False, False, True]

    def test_where_filtered_rows_pass(self):
        ds = Dataset.from_pydict({"x": [1.0, -5.0, 2.0], "g": [1, 2, 1]})
        check = (
            Check(CheckLevel.ERROR, "w")
            .satisfies("x > 0", "pos-in-g1", lambda v: v == 1.0)
            .where("g = 1")
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        col = rl.column(rl.schema.names[0]).to_pylist()
        # row 1 is excluded by the filter -> passes by default
        assert col == [True, True, True]

    def test_asserted_value_outcomes_lengths_and_minmax(self):
        """r4 breadth (VERDICT r3 next #5): MinLength/MaxLength and
        Minimum/Maximum apply the constraint's OWN assertion per row;
        null rows pass (NullBehavior.Ignore) and the aggregate metric
        agrees with the per-row outcomes."""
        ds = Dataset.from_pydict(
            {
                "s": ["a", "abc", None, "abcdef"],
                "x": [5.0, -1.0, 7.0, None],
            }
        )
        check = (
            Check(CheckLevel.ERROR, "asserted")
            .has_min_length("s", lambda v: v >= 2)
            .has_max_length("s", lambda v: v <= 3)
            .has_min("x", lambda v: v >= 0)
            .has_max("x", lambda v: v <= 6)
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        by_name = {
            name: rl.column(name).to_pylist() for name in rl.schema.names
        }
        min_len = next(v for k, v in by_name.items() if "MinLength" in k)
        assert min_len == [False, True, True, True]  # null passes
        max_len = next(v for k, v in by_name.items() if "MaxLength" in k)
        assert max_len == [True, True, True, False]
        has_min = next(v for k, v in by_name.items() if "Minimum" in k)
        assert has_min == [True, False, True, True]
        has_max = next(v for k, v in by_name.items() if "Maximum" in k)
        assert has_max == [True, True, False, True]
        # per-row vs aggregate agreement: the aggregate constraint
        # fails exactly when some real row fails its assertion
        for cr in list(result.check_results.values())[0].constraint_results:
            name = str(cr.constraint)
            row_passed = all(x for x in by_name[name] if x is not None)
            from deequ_tpu.checks.check import ConstraintStatus

            agg_passed = cr.status == ConstraintStatus.SUCCESS
            assert row_passed == agg_passed, name

    def test_filtered_row_outcome_null_semantics(self):
        """filtered_row_outcome='null' yields SQL NULL (not True) for
        where-excluded rows — the reference's NULLED FilteredRowOutcome
        (AnalyzerOptions.filteredRow)."""
        ds = Dataset.from_pydict({"x": [1.0, -5.0, 2.0], "g": [1, 2, 1]})
        check = (
            Check(CheckLevel.ERROR, "w")
            .satisfies("x > 0", "pos-in-g1", lambda v: v == 1.0)
            .where("g = 1")
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset(
            filtered_row_outcome="null"
        ).table
        col = rl.column(rl.schema.names[0]).to_pylist()
        assert col == [True, None, True]
        import pytest as _pytest

        with _pytest.raises(ValueError):
            result.row_level_results_as_dataset(filtered_row_outcome="x")

    def test_throwing_assertion_degrades_to_no_column(self):
        """A partial assertion (raises on some value) must not abort
        the row-level export — the aggregate path already reported the
        exception as a FAILURE ConstraintResult."""
        ds = Dataset.from_pydict({"x": [1.0, 0.0, None]})
        check = Check(CheckLevel.ERROR, "partial").has_min(
            "x", lambda v: 1.0 / v > 0
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        # assertion(0.0) raises -> the column is skipped, not crashed
        assert all("Minimum" not in n for n in rl.schema.names)
