from deequ_tpu.checks.check import (
    Check,
    CheckLevel,
    CheckResult,
    CheckStatus,
    CheckWithLastConstraintFilterable,
    ConstrainableDataTypes,
    is_one,
)

__all__ = [
    "Check",
    "CheckLevel",
    "CheckResult",
    "CheckStatus",
    "CheckWithLastConstraintFilterable",
    "ConstrainableDataTypes",
    "is_one",
]
