"""KLL quantile-sketch metric: bucket distribution + sketch parameters.

Reference: ``src/main/scala/com/amazon/deequ/metrics/KLLMetric.scala``
(SURVEY.md §2.1) — the metric carries a bucketed distribution derived from
the sketch plus the sketch parameters and raw compactor data, so it can be
persisted and re-queried.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.utils.trylike import Success


@dataclass(frozen=True)
class BucketValue:
    low_value: float
    high_value: float
    count: int


@dataclass(frozen=True)
class BucketDistribution:
    """Equi-width bucketing of a KLL sketch plus the sketch internals.

    ``parameters`` = [shrinking_factor, sketch_size] as in the reference;
    ``data`` = the compactor buffers (level -> weighted items).
    """

    buckets: List[BucketValue]
    parameters: Tuple[float, ...]
    data: Tuple[Tuple[float, ...], ...] = field(default=())

    def apx_quantile_from_buckets(self, q: float) -> float:
        total = sum(b.count for b in self.buckets)
        if total == 0:
            return float("nan")
        target = q * total
        running = 0
        for b in self.buckets:
            running += b.count
            if running >= target:
                return b.high_value
        return self.buckets[-1].high_value


@dataclass(frozen=True)
class KLLMetric(Metric[BucketDistribution]):
    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_failure:
            return (
                DoubleMetric(self.entity, self.name, self.instance, self.value),
            )
        dist = self.value.get()
        return tuple(
            DoubleMetric(
                self.entity,
                f"{self.name}.bucket[{i}]",
                self.instance,
                Success(float(b.count)),
            )
            for i, b in enumerate(dist.buckets)
        )

    @staticmethod
    def success(
        name: str, instance: str, dist: BucketDistribution
    ) -> "KLLMetric":
        return KLLMetric(Entity.COLUMN, name, instance, Success(dist))
