"""Preempt-discipline analyzer: no requeue/revoke without evidence.

The conservation invariant of checkpoint-conserving preemption
(docs/SERVICE.md "Preemption and autoscaling"): a run may only be
requeued — and its lease only REVOKED — after the checkpoint-bearing
cancel evidence for the attempt has been extracted via
``preempt_checkpoint_evidence`` (service/preempt.py). A call site that
skips the evidence step can requeue a run that was never preempted
(duplicating its work) or revoke a lease for a run that completed
(losing its result).

The rule is structural, matching how the invariant is written in the
code: inside ``deequ_tpu/service/``, every call to an attribute named
``requeue`` or ``revoke`` must be LEXICALLY PRECEDED, within the same
enclosing function, by a call to ``preempt_checkpoint_evidence`` —
the cancel -> checkpoint-evidence -> revoke/requeue ordering made
checkable. Flow-insensitive on purpose: the evidence helper caches its
verdict on the ticket, so any earlier call in the function establishes
the verdict every later site reads.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

SCOPE_PREFIX = "deequ_tpu/service/"

GUARDED_ATTRS = frozenset({"requeue", "revoke"})
EVIDENCE_NAME = "preempt_checkpoint_evidence"


def _call_name(node: ast.Call) -> Optional[str]:
    """The last path segment of the called name ('requeue' for
    ``self.queue.requeue(...)``), or None for computed callees."""
    name = dotted_name(node.func)
    if name is None:
        return None
    return name.split(".")[-1]


def _function_sites(
    tree: ast.AST,
) -> Iterable[Tuple[Optional[ast.AST], List[ast.Call]]]:
    """(enclosing function, calls directly inside it) pairs; calls in
    nested functions belong to the NESTED function (each scope must
    establish its own evidence), module-level calls to None."""
    functions = [
        node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    owner: dict[int, ast.AST] = {}
    for fn in functions:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                # innermost function wins: walk visits outer functions
                # first, so a later (nested) owner overwrites
                owner[id(node)] = fn
    by_fn: dict[int, List[ast.Call]] = {}
    module_level: List[ast.Call] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = owner.get(id(node))
        if fn is None:
            module_level.append(node)
        else:
            by_fn.setdefault(id(fn), []).append(node)
    for fn in functions:
        yield fn, by_fn.get(id(fn), [])
    if module_level:
        yield None, module_level


class PreemptDisciplineAnalyzer(Analyzer):
    name = "preempt"
    rules = ("preempt-discipline",)
    description = (
        "requeue/revoke call sites in deequ_tpu/service/ not preceded "
        "by checkpoint-evidence extraction"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if not sf.rel.startswith(SCOPE_PREFIX) or sf.tree is None:
                continue
            for fn, calls in _function_sites(sf.tree):
                evidence_lines = [
                    c.lineno
                    for c in calls
                    if _call_name(c) == EVIDENCE_NAME
                ]
                first_evidence = (
                    min(evidence_lines) if evidence_lines else None
                )
                for call in calls:
                    attr = _call_name(call)
                    if attr not in GUARDED_ATTRS:
                        continue
                    if not isinstance(call.func, ast.Attribute):
                        continue  # a local helper, not the queue/placer
                    if (
                        first_evidence is not None
                        and first_evidence < call.lineno
                    ):
                        continue
                    where = (
                        f"function {getattr(fn, 'name', '?')!r}"
                        if fn is not None
                        else "module level"
                    )
                    yield Finding(
                        rule="preempt-discipline",
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            f".{attr}() at {where} without a preceding "
                            f"{EVIDENCE_NAME}() call — requeue/revoke "
                            "is only licensed by checkpoint-bearing "
                            "cancel evidence (docs/SERVICE.md "
                            '"Preemption and autoscaling")'
                        ),
                        symbol=attr,
                    )


register(PreemptDisciplineAnalyzer())
