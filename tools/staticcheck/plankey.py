"""Plan-key discipline analyzer: config reads in the plan-building
path must be covered by the plan-cache key.

The bug class (fixed by hand in PR 6/7 as the "resolved pallas impl
token" patch): ``prepare_scan`` reads a config knob, bakes its value
into the trace, but ``_plan_cache_key`` doesn't carry it — flip the
knob, and the cache serves a plan compiled under the OLD value. This
analyzer closes the loop structurally:

- scope: modules that define ``_plan_cache_key`` (engine/scan.py);
- the plan-building path is every function same-module-reachable from
  ``prepare_scan`` (bare-name and ``self.<method>`` call edges);
- a config read is ``config.options().<attr>`` directly, or
  ``<var>.<attr>`` where ``<var>`` was assigned from
  ``config.options()`` in the same function;
- the covered set is the union of attributes read inside
  ``_plan_cache_key`` itself and the module's
  ``PLAN_KEY_COVERED_CONFIG`` mapping (attr -> one-line justification
  of HOW the key covers it — shape specialization, mode fork, a key
  element). A read outside the covered set is a ``plan-key`` finding.

Adding a config read to the plan path therefore forces a decision at
lint time: thread it into the key, or document in
``PLAN_KEY_COVERED_CONFIG`` why the key already distinguishes it.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

KEY_FUNC = "_plan_cache_key"
ROOT_FUNC = "prepare_scan"
COVERED_CONST = "PLAN_KEY_COVERED_CONFIG"
OPTIONS_CALLS = ("config.options", "options")


def _is_options_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and (dotted_name(node.func) or "") in OPTIONS_CALLS
    )


def _config_reads(func: ast.AST) -> List[Tuple[str, int]]:
    """(attr, line) for every config-option attribute read in one
    function: direct ``config.options().attr`` plus ``opts.attr`` for
    locals assigned from ``config.options()``."""
    opts_vars: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            if isinstance(node.targets[0], ast.Name) and _is_options_call(
                node.value
            ):
                opts_vars.add(node.targets[0].id)
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Attribute):
            continue
        if _is_options_call(node.value):
            reads.append((node.attr, node.lineno))
        elif (
            isinstance(node.value, ast.Name)
            and node.value.id in opts_vars
        ):
            reads.append((node.attr, node.lineno))
    return reads


def _functions(tree: ast.AST) -> Dict[str, ast.AST]:
    """method/function name -> node (flat: the plan path lives in one
    class plus module helpers, and names don't collide in scan.py)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _callees(func: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is None:
                continue
            parts = name.split(".")
            if parts[0] in ("self", "cls") and len(parts) == 2:
                out.add(parts[1])
            elif len(parts) == 1:
                out.add(parts[0])
    return out


def _covered_const(tree: ast.AST) -> Optional[Set[str]]:
    """Keys of the module-level PLAN_KEY_COVERED_CONFIG mapping (or
    elements, when it's a tuple/set), None when absent."""
    for node in tree.body if hasattr(tree, "body") else []:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for target in targets:
            if isinstance(target, ast.Name) and target.id == COVERED_CONST:
                try:
                    literal = ast.literal_eval(value)
                except (ValueError, SyntaxError):
                    return None
                if isinstance(literal, dict):
                    return set(literal.keys())
                return set(literal)
    return None


class PlanKeyAnalyzer(Analyzer):
    name = "plankey"
    rules = ("plan-key",)
    description = (
        "config reads in the prepare_scan plan-building path not "
        "covered by _plan_cache_key / PLAN_KEY_COVERED_CONFIG"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if sf.tree is None:
                continue
            functions = _functions(sf.tree)
            if KEY_FUNC not in functions or ROOT_FUNC not in functions:
                continue
            covered: Set[str] = set(
                attr for attr, _ in _config_reads(functions[KEY_FUNC])
            )
            const = _covered_const(sf.tree)
            if const:
                covered |= const
            # plan path: fixed-point reachability from prepare_scan
            reachable: Set[str] = {ROOT_FUNC, KEY_FUNC}
            frontier = [ROOT_FUNC, KEY_FUNC]
            while frontier:
                name = frontier.pop()
                for callee in _callees(functions[name]):
                    if callee in functions and callee not in reachable:
                        reachable.add(callee)
                        frontier.append(callee)
            for name in sorted(reachable):
                for attr, line in _config_reads(functions[name]):
                    if attr in covered:
                        continue
                    yield Finding(
                        rule="plan-key",
                        path=sf.rel,
                        line=line,
                        message=(
                            f"config read 'options().{attr}' in plan-"
                            f"building path '{name}' is not covered by "
                            f"{KEY_FUNC} or {COVERED_CONST} — a cached "
                            "plan compiled under a different value "
                            "would be served silently"
                        ),
                        symbol=attr,
                    )


register(PlanKeyAnalyzer())
