"""AnalysisRunner: THE engine entry point and pass planner.

Reference: ``src/main/scala/com/amazon/deequ/analyzers/runners/
AnalysisRunner.scala`` (SURVEY.md §2.4, §3.1): dedup analyzers, reuse
repository metrics, check preconditions (failures become failure metrics
immediately), fuse all scan-shareable analyzers into one pass, run one
frequency computation per distinct (grouping columns, filter) shared by
all grouping analyzers over it, assemble an ``AnalyzerContext``, and
optionally aggregate/persist states (the incremental path,
``runOnAggregatedStates``, SURVEY.md §3.2).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.analyzers.base import (
    Analyzer,
    GroupingAnalyzer,
    MetricCalculationException,
    ScanShareableAnalyzer,
    wrap_if_necessary,
)
from deequ_tpu.data.table import Dataset, Schema
from deequ_tpu.engine.memory import oom_probe_of
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.metrics.metric import Metric
from deequ_tpu.telemetry import get_telemetry, merge_summaries
from deequ_tpu.utils.observe import RunMetadata, timed_pass
from deequ_tpu.utils.trylike import Try


# --------------------------------------------------------------------------
# AnalyzerContext
# --------------------------------------------------------------------------


@dataclass
class AnalyzerContext:
    """Map analyzer -> metric (reference: AnalyzerContext.scala), plus
    per-pass wall-time metadata (deequ_tpu.utils.observe — beyond the
    reference, SURVEY.md §5.1) and the raw telemetry run summary it was
    derived from (deequ_tpu.telemetry; None when telemetry is off)."""

    metric_map: Dict[Analyzer, Metric] = field(default_factory=dict)
    run_metadata: Optional["RunMetadata"] = None
    telemetry: Optional[Dict[str, Any]] = None
    # engine.resilience.ScanDegradation when the run's fused scans
    # quarantined batches (docs/RESILIENCE.md); None = clean run
    degradation: Optional[Any] = None
    # engine.deadline.ScanInterruption when the run was cancelled or
    # exhausted its deadline mid-scan — metrics cover only the batches
    # scanned before the interrupt; None = ran to completion
    interruption: Optional[Any] = None

    @staticmethod
    def empty() -> "AnalyzerContext":
        return AnalyzerContext({})

    def all_metrics(self) -> List[Metric]:
        return list(self.metric_map.values())

    def metric(self, analyzer: Analyzer) -> Optional[Metric]:
        return self.metric_map.get(analyzer)

    def subset(self, analyzers: Sequence[Analyzer]) -> "AnalyzerContext":
        """Slice this context down to ``analyzers``, matched by
        :attr:`Analyzer.identity_key` — the projection a coalesced
        superset run uses to hand each tenant exactly what a solo run
        of its suite would have produced. Metrics are matched by
        identity (not object equality) so a tenant's own analyzer
        instances key the returned map; run provenance — metadata,
        telemetry summary, degradation, interruption — is carried
        whole, because it describes the one physical scan every member
        shared."""
        wanted = {a.identity_key: a for a in _dedup(analyzers)}
        sliced = {}
        for have, metric in self.metric_map.items():
            target = wanted.get(have.identity_key)
            if target is not None:
                sliced[target] = metric
        return AnalyzerContext(
            sliced,
            run_metadata=self.run_metadata,
            telemetry=self.telemetry,
            degradation=self.degradation,
            interruption=self.interruption,
        )

    def __add__(self, other: "AnalyzerContext") -> "AnalyzerContext":
        from deequ_tpu.engine.deadline import ScanInterruption
        from deequ_tpu.engine.resilience import ScanDegradation

        merged = dict(self.metric_map)
        merged.update(other.metric_map)
        return AnalyzerContext(
            merged,
            run_metadata=RunMetadata.merge_optional(
                self.run_metadata, other.run_metadata
            ),
            telemetry=merge_summaries([self.telemetry, other.telemetry]),
            degradation=ScanDegradation.merge_optional(
                self.degradation, other.degradation
            ),
            interruption=ScanInterruption.merge_optional(
                self.interruption, other.interruption
            ),
        )

    def success_metrics_as_records(
        self, for_analyzers: Optional[Sequence[Analyzer]] = None
    ) -> List[Dict[str, Any]]:
        """Flat records (entity, instance, name, value) for successful
        metrics — the equivalent of successMetricsAsDataFrame."""
        records = []
        for analyzer, metric in self.metric_map.items():
            if for_analyzers and analyzer not in for_analyzers:
                continue
            for flat in metric.flatten():
                if flat.value.is_success:
                    records.append(
                        {
                            "entity": flat.entity.value,
                            "instance": flat.instance,
                            "name": flat.name,
                            "value": flat.value.get(),
                        }
                    )
        return records

    def success_metrics_as_json(
        self, for_analyzers: Optional[Sequence[Analyzer]] = None
    ) -> str:
        return json.dumps(
            self.success_metrics_as_records(for_analyzers), indent=2
        )

    def success_metrics_as_dataframe(self, for_analyzers=None):
        import pandas as pd

        return pd.DataFrame(
            self.success_metrics_as_records(for_analyzers),
            columns=["entity", "instance", "name", "value"],
        )


# --------------------------------------------------------------------------
# AnalysisRunner
# --------------------------------------------------------------------------


def _dedup(analyzers: Sequence[Analyzer]) -> List[Analyzer]:
    seen = set()
    out = []
    for a in analyzers:
        if a not in seen:
            seen.add(a)
            out.append(a)
    return out


class AnalysisRunner:
    """Static facade mirroring the reference's AnalysisRunner object."""

    @staticmethod
    def on_data(data: Dataset) -> "AnalysisRunBuilder":
        return AnalysisRunBuilder(data)

    @staticmethod
    def do_analysis_run(
        data: Dataset,
        analyzers: Sequence[Analyzer],
        aggregate_with=None,
        save_states_with=None,
        engine: Optional[AnalysisEngine] = None,
        metrics_repository=None,
        reuse_existing_results_for_key=None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key=None,
        deadline=None,
        cancel=None,
        row_sink=None,
    ) -> AnalyzerContext:
        """Run the analysis. ``deadline`` (seconds, or a full
        ``RunBudget``) and ``cancel`` (a ``CancelToken``) bound the run
        (docs/RESILIENCE.md): an interrupt mid-scan still RETURNS — a
        context with partial metrics and ``context.interruption`` set —
        it never raises. Config fallbacks ``run_deadline_seconds`` /
        ``batch_stall_seconds`` apply when no explicit envelope is
        given; ``max_concurrent_runs`` queues runs FIFO, and only a run
        whose envelope closes while still QUEUED raises
        (``DeadlineExceeded``/``RunCancelled``) — it never started, so
        there is nothing partial to return."""
        analyzers = _dedup(analyzers)
        if not analyzers:
            return AnalyzerContext.empty()
        engine = engine or AnalysisEngine()

        from deequ_tpu import config
        from deequ_tpu.engine.deadline import (
            RunBudget,
            admission_controller,
            shutdown_installed,
            shutdown_token,
        )

        opts = config.options()
        # materialize the run's envelope onto the engine: explicit
        # params win, then an engine-attached budget/token (left
        # untouched — the profiler shares ONE across its passes), then
        # the config knobs; restored in finally so one engine can serve
        # bounded and unbounded runs interleaved
        prev_budget, prev_cancel = engine.budget, engine.cancel
        if deadline is not None:
            engine.budget = (
                deadline
                if isinstance(deadline, RunBudget)
                else RunBudget(
                    deadline_s=float(deadline),
                    stall_s=opts.batch_stall_seconds or None,
                )
            )
        elif engine.budget is None and (
            opts.run_deadline_seconds > 0 or opts.batch_stall_seconds > 0
        ):
            engine.budget = RunBudget(
                deadline_s=opts.run_deadline_seconds or None,
                stall_s=opts.batch_stall_seconds or None,
            )
        if cancel is not None:
            engine.cancel = cancel

        admitted = False
        limit = opts.max_concurrent_runs
        # high-watermark gate (docs/RESILIENCE.md "Memory pressure"):
        # with a watermark configured, runs also queue once the SUM of
        # their estimated device footprints would exceed it — queueing
        # instead of co-OOMing. Zero-cost default: no watermark -> no
        # estimate, and with no run limit either, no admission at all
        watermark = opts.memory_watermark_bytes
        est_bytes = 0
        if watermark > 0:
            try:
                est_bytes = engine.estimated_run_bytes(data)
            except Exception:  # noqa: BLE001 — unsized source: no gate
                est_bytes = 0
        try:
            if limit > 0 or (watermark > 0 and est_bytes > 0):
                tokens = [engine.cancel]
                if shutdown_installed():
                    tokens.append(shutdown_token())
                admission_controller().acquire(
                    limit,
                    budget=engine.budget,
                    tokens=tokens,
                    estimated_bytes=est_bytes,
                    watermark_bytes=watermark,
                )
                admitted = True
            return AnalysisRunner._do_admitted_run(
                data,
                analyzers,
                aggregate_with=aggregate_with,
                save_states_with=save_states_with,
                engine=engine,
                metrics_repository=metrics_repository,
                reuse_existing_results_for_key=reuse_existing_results_for_key,
                fail_if_results_missing=fail_if_results_missing,
                save_or_append_results_with_key=save_or_append_results_with_key,
                row_sink=row_sink,
            )
        finally:
            if admitted:
                admission_controller().release(est_bytes)
            engine.budget, engine.cancel = prev_budget, prev_cancel

    @staticmethod
    def do_coalesced_analysis_run(
        data: Dataset,
        suites: Sequence[Sequence[Analyzer]],
        engine: Optional[AnalysisEngine] = None,
        deadline=None,
        cancel=None,
    ) -> List[AnalyzerContext]:
        """One scan, many tenants: union every suite's analyzers, run
        ONE ``do_analysis_run`` over the superset, then :meth:`slice
        <AnalyzerContext.subset>` each suite's context back out.
        Analyzer states are commutative monoids and the fused pass
        already slices each vectorized member's state individually, so
        a superset scan's per-analyzer metrics equal a solo run's by
        construction (pinned differentially in tests/test_coalesce.py).
        Returns one context per input suite, in order."""
        union = _dedup([a for suite in suites for a in suite])
        superset = AnalysisRunner.do_analysis_run(
            data,
            union,
            engine=engine,
            deadline=deadline,
            cancel=cancel,
        )
        return [superset.subset(list(suite)) for suite in suites]

    @staticmethod
    def _do_admitted_run(
        data: Dataset,
        analyzers: Sequence[Analyzer],
        aggregate_with=None,
        save_states_with=None,
        engine: Optional[AnalysisEngine] = None,
        metrics_repository=None,
        reuse_existing_results_for_key=None,
        fail_if_results_missing: bool = False,
        save_or_append_results_with_key=None,
        row_sink=None,
    ) -> AnalyzerContext:
        # fresh degradation record for THIS run; every scan the run
        # issues (shared pass + deferred fallbacks) merges into it
        engine.reset_degradation()
        tm = get_telemetry()
        tm.counter("runner.runs").inc()

        # 1) reuse existing metrics from the repository (SURVEY.md §2.4 (1))
        reused = AnalyzerContext.empty()
        if metrics_repository is not None and reuse_existing_results_for_key is not None:
            existing = metrics_repository.load_by_key(
                reuse_existing_results_for_key
            )
            if existing is not None:
                reusable = {
                    a: m
                    for a, m in existing.analyzer_context.metric_map.items()
                    if a in analyzers
                }
                reused = AnalyzerContext(reusable)
            if fail_if_results_missing and len(reused.metric_map) < len(analyzers):
                missing = [a for a in analyzers if a not in reused.metric_map]
                raise RuntimeError(
                    "Could not find all necessary results in the "
                    f"MetricsRepository, missing: {missing}"
                )
        remaining = [a for a in analyzers if a not in reused.metric_map]

        # 2) preconditions against the schema -> immediate failure metrics
        passed: List[Analyzer] = []
        failures: Dict[Analyzer, Metric] = {}
        for analyzer in remaining:
            exc = _check_preconditions(analyzer, data.schema)
            if exc is not None:
                failures[analyzer] = analyzer.to_failure_metric(exc)
            else:
                passed.append(analyzer)

        # 3) partition into scan-shareable / grouping / direct
        scan_shareable = [
            a for a in passed if isinstance(a, ScanShareableAnalyzer)
        ]
        grouping = [a for a in passed if isinstance(a, GroupingAnalyzer)]
        others = [
            a
            for a in passed
            if not isinstance(a, (ScanShareableAnalyzer, GroupingAnalyzer))
        ]

        metrics: Dict[Analyzer, Metric] = dict(failures)
        # explicit metadata stays the DISABLED-telemetry fallback: with
        # telemetry on, the run capture below supersedes it
        metadata = RunMetadata()
        rows = data.num_rows

        with tm.run("analysis") as cap:
            # 4+5) ONE fused scan for every scan-shareable analyzer AND
            # every dense grouping frequency plan — a mixed verification
            # suite costs a single pass over the data (SURVEY.md §2.4);
            # device-sort/Arrow spill plans run right after, reusing the
            # chunks the shared scan just cached
            if scan_shareable or grouping or row_sink is not None:
                with timed_pass(
                    metadata, "scan", rows,
                    len(scan_shareable) + len(grouping),
                ):
                    metrics.update(
                        _run_fused_pass(
                            data, scan_shareable, grouping, engine,
                            aggregate_with, save_states_with, metadata,
                            row_sink=row_sink,
                        )
                    )

            # 6) schema-only analyzers: failure-to-metric conversion via
            # Try.recover (utils/trylike.py), the reference's idiom —
            # a raising to_failure_metric would surface as the Failure
            for analyzer in others:
                metrics[analyzer] = (
                    Try.of(
                        lambda a=analyzer: a.compute_directly(data)  # type: ignore[attr-defined]
                    )
                    .recover(analyzer.to_failure_metric)
                    .get()
                )

        summary = cap.final
        if summary is not None:
            metadata = RunMetadata.from_telemetry_summary(summary)
        n_failed = sum(
            1
            for m in metrics.values()
            if getattr(getattr(m, "value", None), "is_failure", False)
        )
        if n_failed:
            tm.counter("runner.analyzer_failures").inc(n_failed)
        for analyzer, metric in metrics.items():
            tm.analyzer_computed(analyzer, metric)

        degradation = engine.last_degradation
        if degradation is not None and not degradation.is_degraded:
            if degradation.retries == 0:
                degradation = None  # clean run: no record to carry
        context = reused + AnalyzerContext(
            metrics,
            run_metadata=metadata,
            telemetry=summary,
            degradation=degradation,
            interruption=engine.last_interruption,
        )

        # 7) optionally persist to the metrics repository — including
        # this run's OPERATIONAL records (telemetry.oprecords), so
        # anomaly strategies can trend the system's own throughput
        if metrics_repository is not None and save_or_append_results_with_key is not None:
            from deequ_tpu.repository.base import AnalysisResult
            from deequ_tpu.telemetry import clock as _wall_clock

            _tm = get_telemetry()
            _t0 = _wall_clock()
            current = metrics_repository.load_by_key(
                save_or_append_results_with_key
            )
            combined = (
                current.analyzer_context + context
                if current is not None
                else context
            )
            if summary is not None:
                from deequ_tpu.telemetry.oprecords import operational_metrics

                op = operational_metrics(summary)
                if op:
                    combined = combined + AnalyzerContext(op)
            metrics_repository.save(
                AnalysisResult(save_or_append_results_with_key, combined)
            )
            # traced runs record the repository round trip as a child
            # span — one emit per run, nothing when untraced
            if _tm.current_trace() is not None:
                _tm.emit_span(
                    "persist",
                    _wall_clock() - _t0,
                    dataset_date=getattr(
                        save_or_append_results_with_key, "dataset_date", 0
                    ),
                )

        return context

    @staticmethod
    def run_on_aggregated_states(
        schema: Schema,
        analyzers: Sequence[Analyzer],
        state_loaders: Sequence[Any],
        save_states_with=None,
    ) -> AnalyzerContext:
        """Incremental path: merge persisted states monoidally and compute
        metrics WITHOUT touching data (SURVEY.md §3.2)."""
        analyzers = _dedup(analyzers)
        metrics: Dict[Analyzer, Metric] = {}
        for analyzer in analyzers:
            exc = _check_preconditions(analyzer, schema)
            if exc is not None:
                metrics[analyzer] = analyzer.to_failure_metric(exc)
                continue
            try:
                # load inside the try: a version-mismatch or corrupt
                # state degrades to THIS analyzer's failure metric
                states = [
                    s
                    for loader in state_loaders
                    for s in [loader.load(analyzer)]
                    if s is not None
                ]
                if not states:
                    metrics[analyzer] = analyzer.compute_metric_from_state(None)
                    continue
                merge = _merge_fn_for(states[0])
                # tree fold: O(log N) depth — a left fold over N large
                # frequency states would re-touch the accumulated keys
                # N times (SURVEY.md §3.2's merge is associative, so any
                # shape is valid)
                while len(states) > 1:
                    states = [
                        merge(states[i], states[i + 1])
                        if i + 1 < len(states)
                        else states[i]
                        for i in range(0, len(states), 2)
                    ]
                merged = states[0]
                if save_states_with is not None:
                    save_states_with.persist(analyzer, merged)
                metrics[analyzer] = analyzer.compute_metric_from_state(merged)
            except Exception as exc:  # noqa: BLE001
                metrics[analyzer] = analyzer.to_failure_metric(exc)
        return AnalyzerContext(metrics)


def _merge_fn_for(state: Any):
    """States carry their own dataset-independent merge (monoid)."""
    merge = getattr(type(state), "merge", None)
    if merge is None:
        raise MetricCalculationException(
            f"state type {type(state).__name__} has no merge"
        )
    return merge


def _check_preconditions(
    analyzer: Analyzer, schema: Schema
) -> Optional[BaseException]:
    try:
        for precondition in analyzer.preconditions():
            precondition(schema)
        return None
    except Exception as exc:  # noqa: BLE001
        return wrap_if_necessary(exc)


@dataclass
class FusedPassPlan:
    """The planned (not yet executed) fused pass: vectorized scan
    units, grouping family plans, the combined ``(adapter, ops)`` scan
    pairs ready for ``engine.run_scan``, and the failure metrics
    planning already produced. First-class so a caller (the service's
    warm path, a future plan registry) can plan once, inspect the
    engine-level ``ScanPlan`` it induces, and execute later — the
    compile/execute split at the runner layer."""

    metrics: Dict[Analyzer, Metric]
    units: List[Any]
    by_plan: Dict[Any, List[Analyzer]]
    dense: List[Any]
    collectors: List[Any]
    deferred: Dict[Any, Any]
    scan_pairs: List[Tuple[Any, Any]]
    # row-level egress (deequ_tpu/egress): a RowSinkPlan whose op rides
    # LAST in scan_pairs — its per-batch bit planes host_fold straight
    # into the quarantine writer; None for ordinary runs
    row_sink: Any = None

    @property
    def empty(self) -> bool:
        return not self.scan_pairs and not self.deferred


def _plan_fused_pass(
    data: Dataset,
    analyzers: List[ScanShareableAnalyzer],
    grouping: List[GroupingAnalyzer],
    engine: AnalysisEngine,
    metadata=None,
    row_sink=None,
) -> FusedPassPlan:
    """Phase 1 of the fused pass: vectorize the scan-shareable
    analyzers, plan the grouping frequency passes, and assemble the
    scan pairs. Per-analyzer plan failures (bad predicate, unknown
    column inside an expression) become failure metrics here without
    aborting the shared pass."""
    from deequ_tpu.analyzers.grouping import (
        FrequencyScanAdapter,
        plan_frequency_passes,
        plans_for,
    )
    from deequ_tpu.engine.vectorize import plan_scan_units

    metrics: Dict[Analyzer, Metric] = {}
    units, plan_failures = plan_scan_units(data, analyzers)
    for analyzer, exc in plan_failures.items():
        metrics[analyzer] = analyzer.to_failure_metric(exc)

    by_plan = plans_for(grouping)
    dense, collectors, deferred = [], [], {}
    if by_plan:
        try:
            dense, collectors, deferred = plan_frequency_passes(
                data,
                list(by_plan.keys()),
                engine,
                events=None if metadata is None else metadata.events,
            )
        except Exception as exc:  # noqa: BLE001 — planning failed for
            # the whole grouping family: every grouping analyzer fails
            for group in by_plan.values():
                for analyzer in group:
                    metrics[analyzer] = analyzer.to_failure_metric(exc)
            by_plan, dense, collectors, deferred = {}, [], [], {}

    scan_pairs = (
        [(unit, unit.ops) for unit in units]
        + [
            (FrequencyScanAdapter(requests), ops)
            for (_p, _d, _s, requests, ops) in dense
        ]
        + [
            (FrequencyScanAdapter(spec.requests), spec.ops)
            for spec in collectors
        ]
    )
    if row_sink is not None:
        # the sink op rides LAST so every metric slice keeps its index
        scan_pairs = scan_pairs + [row_sink.scan_pair]
    return FusedPassPlan(
        metrics=metrics,
        units=units,
        by_plan=by_plan,
        dense=dense,
        collectors=collectors,
        deferred=deferred,
        scan_pairs=scan_pairs,
        row_sink=row_sink,
    )


def _run_fused_pass(
    data: Dataset,
    analyzers: List[ScanShareableAnalyzer],
    grouping: List[GroupingAnalyzer],
    engine: AnalysisEngine,
    aggregate_with,
    save_states_with,
    metadata=None,
    row_sink=None,
) -> Dict[Analyzer, Metric]:
    """Plan + run THE fused scan: scan-shareable analyzers (vectorized
    into stacked group ops, engine/vectorize.py), dense grouping
    frequency plans (scatter-add ScanOps, analyzers/grouping.py), AND
    high-cardinality spill plans (one-pass key collectors,
    analyzers/spill.py) all ride one engine.run_scan — one pass over
    the data, one packed state fetch, then every spill plan's sort
    finalize dispatched before any result is fetched so the per-plan
    sorts overlap. Only host-Arrow fallbacks (and collectors disabled
    via config.one_pass_spill) re-read the source. Plan failures
    degrade to failure metrics without aborting the shared pass; each
    vectorized member's ordinary state is sliced back out afterwards,
    so persistence/merge semantics are identical to the single path.
    Composes ``_plan_fused_pass`` + ``_execute_fused_pass`` — the
    runner-layer compile/execute split."""
    pass_plan = _plan_fused_pass(
        data, analyzers, grouping, engine, metadata, row_sink=row_sink
    )
    if pass_plan.empty:
        return pass_plan.metrics
    return _execute_fused_pass(
        pass_plan, data, engine, aggregate_with, save_states_with, metadata
    )


def _execute_fused_pass(
    pass_plan: FusedPassPlan,
    data: Dataset,
    engine: AnalysisEngine,
    aggregate_with,
    save_states_with,
    metadata=None,
) -> Dict[Analyzer, Metric]:
    """Phase 2: drive a planned fused pass — the shared scan, state
    slicing/persistence, grouping finalize, deferred spill fallbacks."""
    from deequ_tpu.analyzers.grouping import (
        finalize_collector_states,
        finalize_dense_states,
        finalize_grouping_metrics,
    )

    metrics = pass_plan.metrics
    units = pass_plan.units
    by_plan = pass_plan.by_plan
    dense = pass_plan.dense
    collectors = pass_plan.collectors
    deferred = pass_plan.deferred
    scan_pairs = pass_plan.scan_pairs
    row_sink = pass_plan.row_sink

    states = None
    if scan_pairs:
        try:
            if row_sink is None:
                states = engine.run_scan(data, scan_pairs)
            else:
                # split phases so the sink learns the scan's quarantine
                # geometry (chunk rows resident / batch rows streaming)
                # BEFORE the first fold hits its writer
                scan_plan = engine.prepare_scan(data, scan_pairs)
                row_sink.bind_scan_geometry(scan_plan, data, engine)
                states = engine.execute_plan(scan_plan, data)
                row_sink.note_scan_complete(engine)
            if metadata is not None and engine.phase_times is not None:
                metadata.events.append(
                    {"event": "scan_phases", **engine.phase_times}
                )
        except Exception as exc:  # noqa: BLE001
            if row_sink is not None:
                row_sink.mark_scan_failed()
            wrapped = wrap_if_necessary(exc)
            for unit in units:
                for analyzer in unit.members:
                    metrics[analyzer] = analyzer.to_failure_metric(wrapped)
            for plan, _dicts, _sizes, _req, _ops in dense:
                for analyzer in by_plan.get(plan, []):
                    metrics[analyzer] = analyzer.to_failure_metric(wrapped)
            dense = []
            # a shared-scan failure must not take the spill plans down
            # with it (they ran independently before one-pass fusion):
            # each collector degrades to its own deferred re-scan
            for spec in collectors:
                deferred[spec.plan] = spec.scan_fallback
            collectors = []

    if states is not None:
        for unit, unit_state in zip(units, states[: len(units)]):
            for member_idx, analyzer in enumerate(unit.members):
                try:
                    if unit.extract is not None:
                        state = unit.extract(unit_state, member_idx)
                        merge = _merge_fn_for(state)
                    else:
                        state = unit_state
                        merge = unit.ops.merge
                    if aggregate_with is not None:
                        prior = aggregate_with.load(analyzer)
                        if prior is not None:
                            state = merge(state, prior)
                    if save_states_with is not None:
                        save_states_with.persist(analyzer, state)
                    metrics[analyzer] = analyzer.compute_metric_from_state(
                        state
                    )
                except Exception as exc:  # noqa: BLE001
                    metrics[analyzer] = analyzer.to_failure_metric(exc)

    # grouping finalize: dense states from the shared scan + deferred
    # spill passes; exceptions stay per-plan (one plan's bad decode
    # must not discard its siblings' valid states)
    frequencies: Dict[Any, Any] = {}
    if states is not None and dense:
        for spec, state in zip(
            dense, states[len(units): len(units) + len(dense)]
        ):
            try:
                frequencies.update(
                    finalize_dense_states([spec], [state])
                )
            except Exception as exc:  # noqa: BLE001
                frequencies[spec[0]] = exc
    if states is not None and collectors:
        # dispatch every plan's sort finalize before fetching any
        # result (finalize_collector_states) so the sorts overlap;
        # isolate: one plan's failure stays its own failure metric;
        # the cancel token lets a cancelled run skip the remaining
        # per-plan device sorts instead of finishing them all
        frequencies.update(
            finalize_collector_states(
                collectors,
                # bounded slice: the row-sink op (when present) rides
                # BEHIND the collectors and must not leak into them
                states[
                    len(units) + len(dense):
                    len(units) + len(dense) + len(collectors)
                ],
                isolate=True,
                cancel=engine.cancel,
                oom_probe=oom_probe_of(data),
            )
        )
    for plan, run in deferred.items():
        # an interrupted run must not start ANOTHER pass over the
        # source — the deferred fallbacks degrade to explicit failure
        # metrics naming the interrupt instead
        if engine.last_interruption is not None:
            frequencies[plan] = MetricCalculationException(
                f"run {engine.last_interruption.kind} before the "
                "deferred frequency pass ran: "
                f"{engine.last_interruption.reason}"
            )
            continue
        try:
            frequencies[plan] = run()
        except Exception as exc:  # noqa: BLE001
            frequencies[plan] = exc
    grouped_plans = {
        plan: group
        for plan, group in by_plan.items()
        if plan in frequencies
    }
    if grouped_plans:
        metrics.update(
            finalize_grouping_metrics(
                grouped_plans, frequencies, aggregate_with,
                save_states_with,
            )
        )
    return metrics


# --------------------------------------------------------------------------
# Builder (reference: AnalysisRunBuilder.scala)
# --------------------------------------------------------------------------


class AnalysisRunBuilder:
    def __init__(self, data: Dataset):
        self._data = data
        self._analyzers: List[Analyzer] = []
        self._engine: Optional[AnalysisEngine] = None
        self._aggregate_with = None
        self._save_states_with = None
        self._repository = None
        self._reuse_key = None
        self._fail_if_results_missing = False
        self._save_key = None
        self._deadline = None
        self._cancel = None

    def add_analyzer(self, analyzer: Analyzer) -> "AnalysisRunBuilder":
        self._analyzers.append(analyzer)
        return self

    def add_analyzers(self, analyzers: Sequence[Analyzer]) -> "AnalysisRunBuilder":
        self._analyzers.extend(analyzers)
        return self

    def with_engine(self, engine: AnalysisEngine) -> "AnalysisRunBuilder":
        self._engine = engine
        return self

    def with_deadline(self, deadline) -> "AnalysisRunBuilder":
        """Bound the run: seconds (float) or a full ``RunBudget``."""
        self._deadline = deadline
        return self

    def with_cancel(self, cancel) -> "AnalysisRunBuilder":
        """Attach a ``CancelToken`` — cancelling it mid-run exits the
        scan cleanly with partial metrics + a resumable checkpoint."""
        self._cancel = cancel
        return self

    def aggregate_with(self, state_loader) -> "AnalysisRunBuilder":
        self._aggregate_with = state_loader
        return self

    def save_states_with(self, state_persister) -> "AnalysisRunBuilder":
        self._save_states_with = state_persister
        return self

    def use_repository(self, repository) -> "AnalysisRunBuilder":
        self._repository = repository
        return self

    def reuse_existing_results_for_key(
        self, key, fail_if_results_missing: bool = False
    ) -> "AnalysisRunBuilder":
        self._reuse_key = key
        self._fail_if_results_missing = fail_if_results_missing
        return self

    def save_or_append_result(self, key) -> "AnalysisRunBuilder":
        self._save_key = key
        return self

    def run(self) -> AnalyzerContext:
        return AnalysisRunner.do_analysis_run(
            self._data,
            self._analyzers,
            aggregate_with=self._aggregate_with,
            save_states_with=self._save_states_with,
            engine=self._engine,
            metrics_repository=self._repository,
            reuse_existing_results_for_key=self._reuse_key,
            fail_if_results_missing=self._fail_if_results_missing,
            save_or_append_results_with_key=self._save_key,
            deadline=self._deadline,
            cancel=self._cancel,
        )
