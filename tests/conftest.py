"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax import,
so every 'distributed' behavior is tested on a fake mesh with no real
cluster — the TPU transfer of the reference's local-Spark fixture
(SURVEY.md §4: SparkContextSpec -> virtual-device mesh)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import pytest  # noqa: E402


@pytest.fixture
def cpu_mesh():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices, ("dp",))
