"""Scan coalescing (PR 12, ROADMAP item 2): sliceable
``AnalyzerContext`` (subset-of-superset == solo, differentially),
composable ``ScanPlan``s (``merge_plans``/``plan_diff``), the queue's
atomic group formation under the coalesce policy, and the service-side
coalescer end to end — K tenant suites over one dataset key share ONE
superset traversal (``engine.data_passes`` pinned), every member's
metrics bit-equal to an independent run, with degradation to
independent execution when the superset scan fails and crash-loop
flooring on every member under isolation."""

import multiprocessing
import threading

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    AnalyzerContext,
    ApproxCountDistinct,
    ApproxQuantile,
    Completeness,
    Compliance,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine import AnalysisEngine
from deequ_tpu.engine.deadline import ManualClock
from deequ_tpu.engine.subproc import CrashLoopError, reset_breakers
from deequ_tpu.engine.scan import (
    coalesce_key_surface,
    merge_plans,
    plan_compatibility,
    plan_diff,
)
from deequ_tpu.repository.base import InMemoryMetricsRepository, ResultKey
from deequ_tpu.service import (
    Priority,
    RunHandle,
    RunQueue,
    RunRequest,
    RunState,
    RunTicket,
    VerificationService,
)
from deequ_tpu.service import service as service_module
from deequ_tpu.service.coalesce import CoalescePolicy
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.verification.suite import VerificationSuite


@pytest.fixture(autouse=True)
def _reaped_and_reset():
    reset_breakers()
    yield
    assert multiprocessing.active_children() == []
    reset_breakers()


def _table(n=4_000, seed=11) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_pydict(
        {
            "a": rng.integers(0, 500, n, dtype=np.int64).tolist(),
            "b": rng.normal(10.0, 3.0, n).tolist(),
            "g": (np.arange(n) % 13).tolist(),
        }
    )


def _values(context: AnalyzerContext):
    out = {}
    for analyzer, metric in context.metric_map.items():
        assert metric.value.is_success, (analyzer, metric.value)
        out[analyzer.identity_key] = metric.value.get()
    return out


def _assert_equal_values(sliced, solo):
    assert sliced.keys() == solo.keys()
    for key in solo:
        a, b = sliced[key], solo[key]
        if isinstance(a, float) and isinstance(b, float):
            # bit-equal: the superset scan runs the SAME fused update
            # over the same batches — no reassociation to forgive
            assert a == b, (key, a, b)
        else:
            assert a == b, (key, a, b)


# --------------------------------------------------------------------------
# Satellite 1: AnalyzerContext.subset — subset-of-superset == solo
# --------------------------------------------------------------------------


class TestContextSubset:
    SUITE = [Completeness("a"), Mean("b"), Minimum("b"), Size()]
    EXTRA = [
        Maximum("b"),
        Sum("a"),
        StandardDeviation("b"),
        Compliance("pos", "b >= 0"),
    ]

    @pytest.mark.parametrize("streamed", [False, True])
    def test_subset_of_superset_equals_solo(self, streamed):
        data = _table()
        overrides = (
            {"device_cache_bytes": 0, "batch_size": 1_024}
            if streamed
            else {}
        )
        with config.configure(**overrides):
            superset = AnalysisRunner.do_analysis_run(
                data, self.SUITE + self.EXTRA, engine=AnalysisEngine()
            )
            solo = AnalysisRunner.do_analysis_run(
                data, self.SUITE, engine=AnalysisEngine()
            )
        sliced = superset.subset(self.SUITE)
        _assert_equal_values(_values(sliced), _values(solo))

    def test_subset_grouping_spill_kll_hll(self):
        """The stateful families too: grouping (frequency passes),
        KLL/HLL sketches — slicing is by analyzer identity, whatever
        machinery computed the metric."""
        suite = [
            Uniqueness(["a"]),
            ApproxQuantile("b", 0.5),
            ApproxCountDistinct("a"),
            Histogram("g"),
        ]
        extra = [Uniqueness(["g"]), ApproxQuantile("b", 0.9), Mean("b")]
        data = _table()
        superset = AnalysisRunner.do_analysis_run(
            data, suite + extra, engine=AnalysisEngine()
        )
        solo = AnalysisRunner.do_analysis_run(
            data, suite, engine=AnalysisEngine()
        )
        sliced = superset.subset(suite)
        assert _values(sliced).keys() == _values(solo).keys()
        for key, value in _values(solo).items():
            got = _values(sliced)[key]
            if isinstance(value, (int, float)):
                assert got == pytest.approx(value, rel=0, abs=0), key
            else:
                assert got == value, key

    def test_subset_where_filtered_analyzers_distinct(self):
        """A where-filtered analyzer is a DIFFERENT identity from its
        unfiltered sibling; subset must never cross the two."""
        data = _table()
        plain = Completeness("a")
        filtered = Completeness("a", where="b >= 10")
        superset = AnalysisRunner.do_analysis_run(
            data, [plain, filtered, Mean("b")], engine=AnalysisEngine()
        )
        only_filtered = superset.subset([filtered])
        assert list(only_filtered.metric_map) == [filtered]
        solo = AnalysisRunner.do_analysis_run(
            data, [filtered], engine=AnalysisEngine()
        )
        _assert_equal_values(_values(only_filtered), _values(solo))

    def test_identity_key_parameter_complete(self):
        assert Completeness("a").identity_key != Completeness("b").identity_key
        assert (
            Completeness("a").identity_key
            != Completeness("a", where="b > 0").identity_key
        )
        assert (
            ApproxQuantile("b", 0.5).identity_key
            != ApproxQuantile("b", 0.9).identity_key
        )
        assert Mean("a").identity_key == Mean("a").identity_key

    def test_subset_carries_scan_provenance(self):
        """Degradation/interruption describe the SHARED scan, so every
        slice keeps them — a tenant must see that its metrics came from
        a partial pass even when another tenant asked for the run."""
        full = AnalysisRunner.do_analysis_run(
            _table(n=256), [Mean("b"), Size()], engine=AnalysisEngine()
        )
        marker = object()
        full.degradation = marker
        full.interruption = marker
        sliced = full.subset([Size()])
        assert sliced.degradation is marker
        assert sliced.interruption is marker
        assert sliced.run_metadata is full.run_metadata
        assert sliced.telemetry is full.telemetry

    def test_coalesced_analysis_run_slices_per_suite(self):
        data = _table()
        suites = [
            [Completeness("a"), Mean("b")],
            [Mean("b"), Maximum("b")],
            [Size()],
        ]
        contexts = AnalysisRunner.do_coalesced_analysis_run(
            data, suites, engine=AnalysisEngine()
        )
        assert len(contexts) == 3
        for suite, context in zip(suites, contexts):
            solo = AnalysisRunner.do_analysis_run(
                data, suite, engine=AnalysisEngine()
            )
            _assert_equal_values(_values(context), _values(solo))


# --------------------------------------------------------------------------
# Plan composability: merge_plans / plan_diff
# --------------------------------------------------------------------------


def _prepare(data, analyzers, engine=None):
    from deequ_tpu.analyzers.runner import _plan_fused_pass

    engine = engine or AnalysisEngine()
    fused = _plan_fused_pass(data, list(analyzers), [], engine)
    plan = engine.prepare_scan(data, fused.scan_pairs)
    assert plan is not None
    return plan


class TestPlanMergeDiff:
    def test_merge_dedups_shared_ops(self):
        data = _table()
        # the shared op must be BEHAVIOR-identical across plans: the
        # vectorizer fuses same-column numeric stats, so Mean("b") solo
        # and Mean+Minimum("b") fused carry different tokens and are
        # (correctly) not dedupable — share the exact analyzer instead
        plan_a = _prepare(data, [Completeness("a"), Mean("b")])
        plan_b = _prepare(data, [Mean("b"), Completeness("g")])
        merged = merge_plans(plan_a, plan_b)
        assert plan_compatibility(plan_a, plan_b) is None
        # the shared Mean("b") op pays ONE slot in the superset
        assert len(merged.ops) < len(plan_a.ops) + len(plan_b.ops)
        diff = plan_diff(plan_a, plan_b)
        assert diff.mergeable
        assert diff.savings >= 1
        assert len(merged.ops) == (
            len(plan_a.ops) + len(plan_b.ops) - diff.savings
        )
        # the merged plan is itself cacheable under a recomputed key
        assert merged.cache_key is not None
        assert merged.cache_key != plan_a.cache_key

    def test_merge_incompatible_raises(self):
        data = _table()
        plan_a = _prepare(data, [Mean("b")], AnalysisEngine(batch_size=512))
        plan_b = _prepare(
            data, [Mean("b")], AnalysisEngine(batch_size=1_024)
        )
        reason = plan_compatibility(plan_a, plan_b)
        assert reason is not None and "batch_size" in reason
        assert not plan_diff(plan_a, plan_b).mergeable
        with pytest.raises(ValueError, match="batch_size"):
            merge_plans(plan_a, plan_b)

    def test_merged_plan_executes_identically(self):
        data = _table()
        engine = AnalysisEngine()
        plan_a = _prepare(data, [Mean("b"), Size()], engine)
        plan_b = _prepare(data, [Mean("b"), Size()], engine)
        merged = merge_plans(plan_a, plan_b)
        assert len(merged.ops) == len(plan_a.ops)
        states_merged = engine.execute_plan(merged, data)
        states_solo = AnalysisEngine().execute_plan(plan_a, data)
        import jax

        for got, want in zip(states_merged, states_solo):
            for leaf_g, leaf_w in zip(
                jax.tree_util.tree_leaves(got),
                jax.tree_util.tree_leaves(want),
            ):
                np.testing.assert_array_equal(
                    np.asarray(leaf_g), np.asarray(leaf_w)
                )

    def test_coalesce_key_surface_tracks_config(self):
        base = coalesce_key_surface()
        with config.configure(batch_size=77):
            assert coalesce_key_surface() != base
        assert coalesce_key_surface() == base


# --------------------------------------------------------------------------
# Queue: atomic group formation under the coalesce policy
# --------------------------------------------------------------------------


_SEQ = iter(range(10_000))


def _ticket(
    tenant="acme",
    priority=Priority.BATCH,
    run_id=None,
    dataset_key="shared",
    surface=("s",),
    submitted_at=0.0,
):
    seq = next(_SEQ)
    handle = RunHandle(run_id or f"run-{seq}", tenant, priority)
    return RunTicket(
        seq=seq,
        handle=handle,
        payload=None,
        dataset_key=dataset_key,
        submitted_at=submitted_at,
        coalesce_surface=surface,
    )


def _policy(window_s=0.0, max_members=8):
    return CoalescePolicy(
        enabled=True, window_s=window_s, max_members=max_members
    )


class TestQueueGrouping:
    def test_group_forms_atomically_from_coqueued(self):
        q = RunQueue(clock=ManualClock())
        tickets = [_ticket(tenant=f"t{i}") for i in range(3)]
        for t in tickets:
            q.push(t)
        group = q.pop_group(should_stop=lambda: True, policy=_policy())
        assert [t.handle.run_id for t in group] == [
            t.handle.run_id for t in tickets
        ]
        assert q.depth() == 0
        for t in group:
            q.task_done(t)

    def test_interactive_never_waits_never_coalesces(self):
        q = RunQueue(clock=ManualClock())
        inter = _ticket(priority=Priority.INTERACTIVE)
        batch = _ticket(priority=Priority.BATCH)
        q.push(batch)
        q.push(inter)
        # interactive pops FIRST (priority) and pops ALONE, even with a
        # compatible batch ticket on the same key
        group = q.pop_group(
            should_stop=lambda: True, policy=_policy(window_s=100.0)
        )
        assert len(group) == 1
        assert group[0] is inter

    def test_window_holds_batch_for_peers_then_releases(self):
        clock = ManualClock()
        q = RunQueue(clock=clock)
        lone = _ticket(submitted_at=clock.now())
        q.push(lone)
        policy = _policy(window_s=5.0)
        # inside the window with room for more members: held back
        assert q.pop_group(should_stop=lambda: True, policy=policy) is None
        assert q.depth() == 1
        # window expired: taken solo
        clock.advance(6.0)
        group = q.pop_group(should_stop=lambda: True, policy=policy)
        assert [t for t in group] == [lone]

    def test_window_releases_when_group_is_full(self):
        clock = ManualClock()
        q = RunQueue(clock=clock)
        a = _ticket(submitted_at=clock.now())
        b = _ticket(submitted_at=clock.now())
        q.push(a)
        q.push(b)
        # max_members=2 and 2 compatible tickets: no point waiting
        group = q.pop_group(
            should_stop=lambda: True,
            policy=_policy(window_s=100.0, max_members=2),
        )
        assert group is not None and len(group) == 2

    def test_max_members_caps_group(self):
        q = RunQueue(clock=ManualClock())
        tickets = [_ticket() for _ in range(5)]
        for t in tickets:
            q.push(t)
        group = q.pop_group(
            should_stop=lambda: True, policy=_policy(max_members=3)
        )
        assert len(group) == 3
        assert q.depth() == 2

    def test_mismatched_key_or_surface_not_absorbed(self):
        q = RunQueue(clock=ManualClock())
        host = _ticket(dataset_key="k1", surface=("s1",))
        other_key = _ticket(dataset_key="k2", surface=("s1",))
        other_surface = _ticket(dataset_key="k1", surface=("s2",))
        no_key = _ticket(dataset_key=None, surface=("s1",))
        for t in (host, other_key, other_surface, no_key):
            q.push(t)
        group = q.pop_group(should_stop=lambda: True, policy=_policy())
        assert group == [host]
        assert q.depth() == 3

    def test_tenant_active_quota_bounds_group(self):
        q = RunQueue(clock=ManualClock(), tenant_max_active=1)
        a1 = _ticket(tenant="acme")
        a2 = _ticket(tenant="acme")
        g1 = _ticket(tenant="globex")
        for t in (a1, a2, g1):
            q.push(t)
        group = q.pop_group(should_stop=lambda: True, policy=_policy())
        # acme's second ticket would breach its active quota inside the
        # group too — quotas bound coalesced admission exactly like solo
        assert group == [a1, g1]

    def test_disabled_policy_degrades_to_solo_pop(self):
        q = RunQueue(clock=ManualClock())
        for _ in range(2):
            q.push(_ticket())
        group = q.pop_group(
            should_stop=lambda: True,
            policy=CoalescePolicy(enabled=False),
        )
        assert len(group) == 1


# --------------------------------------------------------------------------
# Service end to end: one pass, many tenants
# --------------------------------------------------------------------------


def _suite(i):
    check = Check(CheckLevel.ERROR, f"tenant-{i}").is_complete("a")
    if i % 2 == 0:
        check = check.is_non_negative("a")
    else:
        check = check.is_complete("b")
    return [check]


class TestServiceCoalescing:
    def _submit_all_then_start(self, svc, n, **request_kwargs):
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=_suite(i),
                    dataset_key="shared/coalesce",
                    dataset_factory=lambda: _table(),
                    priority=Priority.BATCH,
                    **request_kwargs,
                )
            )
            for i in range(n)
        ]
        svc.start()
        return handles

    def test_one_pass_metrics_equal_independent(self):
        tm = get_telemetry()
        solo = [
            VerificationSuite.do_verification_run(_table(), _suite(i))
            for i in range(3)
        ]
        passes_before = tm.counter("engine.data_passes").value
        coalesced_before = tm.counter("service.runs_coalesced").value
        saved_before = tm.counter("service.scan_passes_saved").value
        svc = VerificationService(
            workers=2,
            interactive_reserve=1,
            coalesce=True,
            coalesce_window_s=0.0,
        )
        handles = self._submit_all_then_start(svc, 3)
        try:
            results = [h.result(timeout=300) for h in handles]
        finally:
            svc.stop(drain=False, timeout=30)
        # THE acceptance pin: 3 tenant runs, ONE traversal of the source
        assert (
            tm.counter("engine.data_passes").value - passes_before == 1
        )
        assert (
            tm.counter("service.runs_coalesced").value - coalesced_before
            == 3
        )
        assert (
            tm.counter("service.scan_passes_saved").value - saved_before
            == 2
        )
        for want, got in zip(solo, results):
            assert got.status == want.status
            _assert_equal_values(
                _values(AnalyzerContext(dict(got.metrics))),
                _values(AnalyzerContext(dict(want.metrics))),
            )
            # every member keeps its OWN check evaluation
            assert {c.description for c in got.check_results} == {
                c.description for c in want.check_results
            }

    def test_members_persist_to_their_own_repositories(self):
        repos = [InMemoryMetricsRepository() for _ in range(2)]
        keys = [ResultKey.of(1000 + i) for i in range(2)]
        svc = VerificationService(
            workers=1, coalesce=True, coalesce_window_s=0.0
        )
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=_suite(i),
                    dataset_key="shared/persist",
                    dataset_factory=lambda: _table(),
                    priority=Priority.BATCH,
                    metrics_repository=repos[i],
                    result_key=keys[i],
                )
            )
            for i in range(2)
        ]
        svc.start()
        try:
            for h in handles:
                h.result(timeout=300)
        finally:
            svc.stop(drain=False, timeout=30)
        for i, (repo, key) in enumerate(zip(repos, keys)):
            saved = repo.load_by_key(key)
            assert saved is not None
            solo = VerificationSuite.do_verification_run(
                _table(), _suite(i)
            )
            saved_values = _values(saved.analyzer_context)
            for ikey, value in _values(
                AnalyzerContext(dict(solo.metrics))
            ).items():
                assert saved_values[ikey] == value, ikey

    def test_superset_failure_degrades_to_independent(self, monkeypatch):
        tm = get_telemetry()
        fallbacks_before = tm.counter("service.coalesce_fallbacks").value

        def boom(*args, **kwargs):
            raise RuntimeError("superset scan exploded")

        monkeypatch.setattr(
            VerificationSuite, "do_coalesced_verification_run", boom
        )
        svc = VerificationService(
            workers=1, coalesce=True, coalesce_window_s=0.0
        )
        handles = self._submit_all_then_start(svc, 3)
        try:
            results = [h.result(timeout=300) for h in handles]
        finally:
            svc.stop(drain=False, timeout=30)
        # every member still completed — independently
        assert all(r.status == CheckStatus.SUCCESS for r in results)
        assert (
            tm.counter("service.coalesce_fallbacks").value
            - fallbacks_before
            == 1
        )

    def test_coalescing_off_by_default(self):
        svc = VerificationService(workers=1)
        assert svc.coalesce_policy is None
        svc2 = VerificationService(
            workers=1, coalesce=True, coalesce_window_s=2.5
        )
        assert svc2.coalesce_policy is not None
        assert svc2.coalesce_policy.window_s == 2.5

    def test_dataset_key_defaults_to_fingerprint(self):
        """Satellite 6: the default dataset_key derives from the
        dataset's content fingerprint, so two requests over the same
        table coalesce (and share the cache) without the caller naming
        the key — ``id()`` never matched across submissions."""
        data = _table(seed=3)
        r1 = RunRequest(tenant="a", checks=(), dataset=data)
        r2 = RunRequest(tenant="b", checks=(), dataset=data)
        assert r1.dataset_key == r2.dataset_key
        assert r1.dataset_key == f"dataset-{data.fingerprint()}"


# --------------------------------------------------------------------------
# Satellite 2: coalescing under isolated execution
# --------------------------------------------------------------------------


def _iso_table():
    return _table(n=2_000, seed=23)


def _analyzer_suite(i):
    base = [Completeness("a"), Mean("b")]
    return base + ([Maximum("b")] if i % 2 == 0 else [Minimum("b")])


def _child_crash(payload):
    from deequ_tpu.testing.faults import hard_crash

    hard_crash(payload.get("signum"))


class TestIsolatedCoalescing:
    def _service(self):
        return VerificationService(
            workers=1, isolated=True, coalesce=True, coalesce_window_s=0.0
        )

    def _submit(self, svc, n=3):
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=(),
                    required_analyzers=_analyzer_suite(i),
                    dataset_key="shared/iso",
                    dataset_factory=_iso_table,
                    priority=Priority.BATCH,
                )
            )
            for i in range(n)
        ]
        svc.start()
        return handles

    def test_one_child_per_superset_scan(self):
        """The whole group crosses ONE process boundary: a single child
        runs the superset scan and the member results come back in
        order, equal to independent runs."""
        tm = get_telemetry()
        passes_before = tm.counter("engine.data_passes").value
        coalesced_before = tm.counter("service.coalesced_scans").value
        svc = self._service()
        handles = self._submit(svc, n=3)
        try:
            results = [h.result(timeout=300) for h in handles]
        finally:
            svc.stop(drain=False, timeout=30)
        assert (
            tm.counter("service.coalesced_scans").value
            - coalesced_before
            == 1
        )
        # the child's fold-back summary carries its counters: ONE
        # traversal total, in ONE child, for all three members
        assert (
            tm.counter("engine.data_passes").value - passes_before == 1
        )
        for i, result in enumerate(results):
            solo = AnalysisRunner.do_analysis_run(
                _iso_table(), _analyzer_suite(i), engine=AnalysisEngine()
            )
            _assert_equal_values(
                _values(AnalyzerContext(dict(result.metrics))),
                _values(solo),
            )

    def _crash_looped_service(self, monkeypatch):
        svc = self._service()
        monkeypatch.setattr(
            svc, "_group_isolation_payload", lambda tickets: {"signum": None}
        )
        monkeypatch.setattr(
            service_module, "_isolated_execute_coalesced", _child_crash
        )
        return svc

    def test_crash_loop_floors_every_member(self, monkeypatch):
        with config.configure(
            degradation_policy="warn",
            crash_max_relaunches=1,
            crash_breaker_cooldown_s=0,
        ):
            svc = self._crash_looped_service(monkeypatch)
            handles = self._submit(svc, n=3)
            try:
                results = [h.result(timeout=300) for h in handles]
            finally:
                svc.stop(drain=False, timeout=30)
        for handle, result in zip(handles, results):
            assert handle.status == RunState.DONE
            assert result.status == CheckStatus.WARNING
            assert result.metrics == {}
            failure = result.degradation.failures[0]
            assert failure.error_class == "CrashLoopError"
            assert failure.attempts >= 1

    def test_crash_loop_policy_fail_fails_every_member(self, monkeypatch):
        with config.configure(
            degradation_policy="fail",
            crash_max_relaunches=1,
            crash_breaker_cooldown_s=0,
        ):
            svc = self._crash_looped_service(monkeypatch)
            handles = self._submit(svc, n=2)
            try:
                for handle in handles:
                    assert handle.wait(timeout=300)
                    assert handle.status == RunState.FAILED
                    with pytest.raises(CrashLoopError):
                        handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=30)
