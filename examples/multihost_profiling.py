"""Multi-host execution over loopback: the SURVEY §7 stage-8 story.

The reference scales by running one Spark executor per host; deequ_tpu
scales the same workload shape with one JAX process per host
(SURVEY.md §2.6, docs/MULTIHOST.md): every host profiles ITS OWN shard
of the table, persists the resulting analyzer STATES (the mergeable
monoids, not the metrics), and any process folds the states into
whole-table metrics with ``run_on_aggregated_states`` — metric-exact,
no row ever crosses hosts.

This script EXECUTES that design with two real processes on this
machine, each calling ``jax.distributed.initialize`` against a loopback
coordinator (the same call a real pod uses with a head-node address):

    python examples/multihost_profiling.py

It writes a two-shard parquet table, spawns the two workers, waits for
both, merges their persisted states, and asserts the merged metrics
equal a single-process run over the whole table.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)  # run from a source checkout w/o installing


# the sweep covers every state family: scan monoids (Size/Mean/Std/
# Completeness), grouping frequencies (CountDistinct/Uniqueness/
# Entropy/Histogram), sketches (HLL numeric + string, KLL), LUT
# counts (DataType), and CustomSql's universal cells (VERDICT r4
# weak #5: sweep analyzer families, not just basic stats)
ANALYZER_SRC = (
    "[Size(), Mean('x'), StandardDeviation('x'), Completeness('x'), "
    "CountDistinct('k'), Uniqueness('k'), Entropy('s'), "
    "Histogram('s'), ApproxCountDistinct('k'), "
    "ApproxCountDistinct('s'), ApproxQuantile('x', 0.5), "
    "DataType('s'), CustomSql('SUM(x) / COUNT(*)')]"
)

_ANALYZER_IMPORTS = """
from deequ_tpu.analyzers import (
    AnalysisRunner, ApproxCountDistinct, ApproxQuantile, Completeness,
    CountDistinct, CustomSql, Entropy, Histogram, Mean, Size,
    StandardDeviation, Uniqueness,
)
from deequ_tpu.analyzers.datatype import DataType
"""

WORKER = r"""
import sys
import jax

coordinator, process_id, shard_path, state_dir = sys.argv[1:5]
# order matters: platform + distributed BEFORE any backend init
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator,
    num_processes=2,
    process_id=int(process_id),
)
assert jax.process_count() == 2, jax.process_count()

from deequ_tpu import Dataset, FileSystemStateProvider
_IMPORTS

dataset = Dataset.from_parquet(shard_path)
AnalysisRunner.do_analysis_run(
    dataset,
    ANALYZERS,
    save_states_with=FileSystemStateProvider(state_dir),
)
print(f"worker {process_id}: states persisted", flush=True)
""".replace("ANALYZERS", ANALYZER_SRC).replace("_IMPORTS", _ANALYZER_IMPORTS)


def main() -> None:
    import shutil

    workdir = tempfile.mkdtemp(prefix="deequ_tpu_multihost_")
    try:
        _run(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _run(workdir: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(3)
    n = 60_000
    x = rng.normal(10.0, 2.0, n).astype(object)
    x[::11] = None
    k = rng.integers(0, 20_000, n, dtype=np.int64)
    s = rng.choice(["1", "2.5", "x", "true", "", "seven"], n)
    table = pa.table(
        {"x": pa.array(list(x), pa.float64()), "k": k, "s": s}
    )

    # UNEQUAL shards (40%/60%): state merges must not assume equal
    # per-host row counts (weighted means, KLL compactions)
    split = int(n * 0.4)
    shards = []
    for i, (off, length) in enumerate([(0, split), (split, n - split)]):
        path = os.path.join(workdir, f"shard{i}.parquet")
        pq.write_table(table.slice(off, length), path)
        shards.append(path)

    with socket.socket() as s:  # free loopback port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    state_dirs = [os.path.join(workdir, f"states{i}") for i in range(2)]
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coordinator, str(i),
             shards[i], state_dirs[i]],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    # wait on BOTH with a shared deadline: when one worker dies, its
    # sibling hangs in distributed collectives — kill it and report the
    # real failure's output, not a timeout
    import time as _time

    deadline = _time.monotonic() + 300
    outputs = [b"", b""]
    try:
        for i, p in enumerate(procs):
            try:
                outputs[i], _ = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                pass  # judged below after every worker is reaped
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if p.poll() is None or not outputs[i]:
                try:
                    extra, _ = p.communicate(timeout=10)
                    outputs[i] = outputs[i] + (extra or b"")
                except Exception:  # noqa: BLE001 — reporting only
                    pass
    failed = [i for i, p in enumerate(procs) if p.returncode != 0]
    if failed:
        report = "\n".join(
            f"--- worker {i} (rc={procs[i].returncode}) ---\n"
            + outputs[i].decode(errors="replace")
            for i in range(2)
        )
        raise RuntimeError(f"worker(s) {failed} failed:\n{report}")

    # any process (here: this one) folds the persisted per-host states
    from deequ_tpu import Dataset, FileSystemStateProvider

    exec(_ANALYZER_IMPORTS, globals())

    analyzers = eval(ANALYZER_SRC)  # same set the workers ran
    whole = Dataset.from_arrow(table)
    merged = AnalysisRunner.run_on_aggregated_states(
        whole.schema,
        analyzers,
        [FileSystemStateProvider(d) for d in state_dirs],
    )
    single = AnalysisRunner.do_analysis_run(whole, analyzers)
    xs = np.sort(np.array([v for v in x if v is not None], dtype=np.float64))
    for a in analyzers:
        got = merged.metric(a).value.get()
        want = single.metric(a).value.get()
        if hasattr(got, "values"):  # Histogram / DataType distribution
            gd = {key: v.absolute for key, v in got.values.items()}
            wd = {key: v.absolute for key, v in want.values.items()}
            assert gd == wd, (a, gd, wd)
            print(f"{a.name:>22}: merged distribution == single")
        elif a.name.startswith("ApproxQuantile"):
            # a merge of per-host KLL sketches is a DIFFERENT (valid)
            # sketch than the single-pass one: hold both to the
            # rank-error envelope around the exact quantile
            for q in (got, want):
                rank = float(np.searchsorted(xs, q)) / len(xs)
                assert abs(rank - 0.5) < 0.02, (a, q, rank)
            print(f"{a.name:>22}: merged {got:.6f} ~ single {want:.6f} "
                  "(rank envelope)")
        else:
            assert abs(got - want) <= 1e-9 * max(1.0, abs(want)), (
                a, got, want,
            )
            print(f"{a.name:>22}: merged {got:.6f} == single {want:.6f}")
    print("multi-host (2 processes, loopback): merged == whole-table")


if __name__ == "__main__":
    main()
