from deequ_tpu.schema.validator import (
    ColumnDefinition,
    DecimalColumnDefinition,
    FractionalColumnDefinition,
    IntColumnDefinition,
    RowLevelSchema,
    RowLevelSchemaValidationResult,
    RowLevelSchemaValidator,
    StringColumnDefinition,
    TimestampColumnDefinition,
)

__all__ = [
    "ColumnDefinition",
    "DecimalColumnDefinition",
    "FractionalColumnDefinition",
    "IntColumnDefinition",
    "RowLevelSchema",
    "RowLevelSchemaValidationResult",
    "RowLevelSchemaValidator",
    "StringColumnDefinition",
    "TimestampColumnDefinition",
]
