from deequ_tpu.repository.base import (
    AnalysisResult,
    InMemoryMetricsRepository,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.fs import FileSystemMetricsRepository

__all__ = [
    "AnalysisResult",
    "FileSystemMetricsRepository",
    "InMemoryMetricsRepository",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
]
