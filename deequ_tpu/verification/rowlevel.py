"""Row-level verification results: per-row pass/fail per constraint.

Reference: newer-upstream row-level results (SURVEY.md §2.2
"FilteredRowOutcome", ``VerificationResult.rowLevelResultsAsDataFrame``):
row-level-capable analyzers also emit a per-row boolean outcome column.
Supported here: Completeness, Compliance (and every Check method that
compiles to it: is_contained_in, is_non_negative, satisfies, ...),
PatternMatch (and contains_email/url/...), Uniqueness. Rows excluded by
a ``where`` filter count as passing (the reference's default
FilteredRowOutcome is non-failing).

Outcomes are computed vectorized — device ops for predicate/mask work,
one host ``np.unique`` pass for uniqueness — never per-row Python.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
import pyarrow as pa

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.basic import Completeness, Compliance, PatternMatch
from deequ_tpu.analyzers.grouping import Uniqueness
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind, ROW_MASK
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    ConstraintDecorator,
)
from deequ_tpu.sql.predicate import compile_predicate


def _full_batch(data: Dataset, requests) -> Dict[str, np.ndarray]:
    batch = {r.key: data.materialize(r) for r in requests}
    for r in requests:
        mask_key = f"{r.column}::mask"
        if mask_key not in batch:
            batch[mask_key] = data.materialize(
                ColumnRequest(r.column, "mask")
            )
    batch[ROW_MASK] = np.ones(data.num_rows, dtype=bool)
    return batch


def _where_pass(where: Optional[str], data: Dataset) -> Optional[np.ndarray]:
    """True for rows EXCLUDED by the filter (they pass by default)."""
    if where is None:
        return None
    pred = compile_predicate(where, data)
    batch = _full_batch(data, pred.requests)
    return ~np.asarray(jax.device_get(pred.complies(batch)), dtype=bool)


def _outcome_for(analyzer: Analyzer, data: Dataset) -> Optional[np.ndarray]:
    if isinstance(analyzer, Completeness):
        mask = data.materialize(ColumnRequest(analyzer.column, "mask"))
        out = np.asarray(mask, dtype=bool).copy()
    elif isinstance(analyzer, Compliance):
        pred = compile_predicate(analyzer.predicate, data)
        batch = _full_batch(data, pred.requests)
        out = np.asarray(
            jax.device_get(pred.complies(batch)), dtype=bool
        ).copy()
    elif isinstance(analyzer, PatternMatch):
        import re

        codes = data.materialize(ColumnRequest(analyzer.column, "codes"))
        mask = data.materialize(ColumnRequest(analyzer.column, "mask"))
        dictionary = data.dictionary(analyzer.column)
        prog = re.compile(analyzer.pattern)
        lut = np.zeros(max(len(dictionary), 1) + 1, dtype=bool)
        for i, value in enumerate(dictionary):
            if value is not None and prog.search(str(value)):
                lut[i] = True
        idx = np.where(codes < 0, len(lut) - 1, codes)
        out = lut[np.clip(idx, 0, len(lut) - 1)] & np.asarray(
            mask, dtype=bool
        )
    elif isinstance(analyzer, Uniqueness):
        columns = analyzer.grouping_columns()
        # fold columns into one exact group id via successive np.unique
        # in each column's NATIVE dtype — no float64 cast (int64 ids
        # above 2^53 must stay distinct, exactly like the HLL hashing)
        group_ids: Optional[np.ndarray] = None
        for c in columns:
            kind = data.schema.kind_of(c)
            repr_name = "codes" if kind == Kind.STRING else "values"
            values = np.asarray(data.materialize(ColumnRequest(c, repr_name)))
            mask = np.asarray(
                data.materialize(ColumnRequest(c, "mask")), dtype=bool
            )
            _, col_ids = np.unique(values, return_inverse=True)
            # validity joins the key so NULL is its own value,
            # distinct from the zero-fill
            col_ids = col_ids * 2 + mask.astype(np.int64)
            if group_ids is None:
                group_ids = col_ids
            else:
                pair = np.stack([group_ids, col_ids], axis=1)
                _, group_ids = np.unique(
                    pair, axis=0, return_inverse=True
                )
        _, inverse, counts = np.unique(
            group_ids, return_inverse=True, return_counts=True
        )
        out = counts[inverse] == 1
    else:
        return None

    excluded = _where_pass(getattr(analyzer, "where", None), data)
    if excluded is not None:
        out = out | excluded
    return out


def row_level_results(check_results, data: Dataset) -> Dataset:
    """One boolean column per row-level-capable constraint, named by the
    constraint, over ``data`` (the dataset the suite ran on)."""
    columns: Dict[str, pa.Array] = {}
    for check, result in check_results.items():
        for cr in result.constraint_results:
            constraint = cr.constraint
            if isinstance(constraint, ConstraintDecorator):
                inner = constraint.inner
            else:
                inner = constraint
            if not isinstance(inner, AnalysisBasedConstraint):
                continue
            outcome = _outcome_for(inner.analyzer, data)
            if outcome is None:
                continue
            columns[str(constraint)] = pa.array(outcome)
    if not columns:
        return Dataset(pa.table({"__no_row_level_constraints__": pa.array([], pa.bool_())}))
    return Dataset(pa.table(columns))
