"""Exactness-golden SPEC: the fixtures and analyzer cases whose exact
metric values are frozen in ``tests/goldens/*.json``.

Purpose (SURVEY.md §7 hard part 4): deequ's value semantics — null
handling, NaN, -0.0, COUNT(col) vs COUNT(*), empty tables, single
rows, all-null columns — must be PINNED as versioned expected-value
files, so (a) any refactor that silently drifts a metric fails the
loader test, and (b) the day ``/root/reference`` is populated, the
frozen values can be diffed against the real reference's outputs
case by case (``tools/recite_reference.py`` prints the checklist).

The spec lives HERE (one module) and is imported by both the
generator (``tools/make_goldens.py``) and the loader test
(``tests/test_goldens.py``) — two copies would drift.

Encoding notes:
- floats serialize via ``encode_value`` (NaN/±inf as strings, -0.0
  distinguished from +0.0 via the sign bit) so JSON round-trips are
  exact;
- each case expects either ``{"success": true, "value": ...}`` or
  ``{"success": false, "error": "<ExceptionTypeName>"}`` — failures
  ARE semantics (deequ returns failure metrics as values, never
  throws; SURVEY.md §2.1).
"""

from __future__ import annotations

import math
from typing import Any, Dict

import numpy as np
import pyarrow as pa

GOLDEN_VERSION = 1

# ---------------------------------------------------------------------------
# fixtures: name -> pyarrow table builder
# ---------------------------------------------------------------------------


def _t(d) -> pa.Table:
    return pa.table(d)


def fixtures() -> Dict[str, pa.Table]:
    return {
        # nulls vs values: COUNT(col)-style metrics see 3 of 5 rows
        "nulls_basic": _t(
            {
                "x": pa.array([1.0, None, 3.0, None, 5.0], pa.float64()),
                "s": pa.array(["a", "b", None, "b", "a"]),
                "k": pa.array([1, 2, 3, 4, 5], pa.int64()),
            }
        ),
        # literal NaN VALUES (not nulls): aggregate semantics must
        # treat NaN as a present value (propagates into Mean/Sum like
        # Spark's avg/sum over NaN; distinct from SQL NULL)
        "nan_values": _t(
            {
                "x": pa.array(
                    [1.0, float("nan"), 3.0], pa.float64()
                ),
            }
        ),
        # -0.0 vs +0.0: equal as numbers (SQL/IEEE ==), so
        # distinctness-family must count ONE group; min/max NORMALIZE
        # -0.0 to 0.0 (Spark's NormalizeFloatingNumbers — also
        # backend-independent, the TPU min lowering drops the sign)
        "neg_zero": _t(
            {
                "x": pa.array([-0.0, 0.0, -0.0], pa.float64()),
            }
        ),
        # pre-encoded float dictionary holding BOTH zeros as distinct
        # entries: normalization must re-unify the codes
        "neg_zero_dict": pa.table(
            {
                "x": pa.array(
                    [-0.0, 0.0, -0.0, 1.5], pa.float64()
                ).dictionary_encode(),
            }
        ),
        # ALL values are literal NaN (none null): Spark's ordering
        # makes NaN the min AND max of an all-NaN column
        "all_nan": _t(
            {
                "x": pa.array([float("nan")] * 3, pa.float64()),
            }
        ),
        "empty": _t(
            {
                "x": pa.array([], pa.float64()),
                "s": pa.array([], pa.string()),
            }
        ),
        "single_row": _t(
            {
                "x": pa.array([42.5], pa.float64()),
                "s": pa.array(["only"], pa.string()),
            }
        ),
        "all_null": _t(
            {
                "x": pa.array([None, None, None], pa.float64()),
                "s": pa.array([None, None, None], pa.string()),
            }
        ),
        # degenerate second-moment shapes: constant column (zero
        # variance), zero-sum denominator, correlated/identical pairs
        "moments_edge": _t(
            {
                "const": pa.array([7.0, 7.0, 7.0, 7.0], pa.float64()),
                "lin": pa.array([1.0, 2.0, 3.0, 4.0], pa.float64()),
                "zsum": pa.array([-2.0, -1.0, 1.0, 2.0], pa.float64()),
                "g1": pa.array(["a", "a", "b", "b"]),
                "g2": pa.array(["x", "x", "y", "y"]),
                "g3": pa.array(["p", "q", "p", "q"]),
            }
        ),
        # COUNT(col) vs COUNT(*): where-filtered Size counts kept ROWS
        # (null x included); Completeness counts non-null OF kept rows
        "count_col_vs_star": _t(
            {
                "x": pa.array([1.0, None, 3.0, None], pa.float64()),
                "grp": pa.array(["a", "a", "b", "b"]),
            }
        ),
        # strings with padding-sensitive lengths + mixed types
        "strings": _t(
            {
                "s": pa.array(["", "ab", None, "abcd", "ab"]),
            }
        ),
    }


# ---------------------------------------------------------------------------
# cases: (fixture, analyzer-spec) pairs; analyzer specs are built by
# the shared factory below so the generator and test construct the
# EXACT same analyzer objects
# ---------------------------------------------------------------------------


def build_analyzer(spec: Dict[str, Any]):
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        Compliance,
        Correlation,
        MutualInformation,
        RatioOfSums,
        CountDistinct,
        DataType,
        Distinctness,
        Entropy,
        Maximum,
        MaxLength,
        Mean,
        Minimum,
        MinLength,
        PatternMatch,
        Size,
        StandardDeviation,
        Sum,
        Uniqueness,
        UniqueValueRatio,
    )

    kinds = {
        "Size": lambda s: Size(where=s.get("where")),
        "Completeness": lambda s: Completeness(
            s["column"], where=s.get("where")
        ),
        "Mean": lambda s: Mean(s["column"], where=s.get("where")),
        "Sum": lambda s: Sum(s["column"], where=s.get("where")),
        "Minimum": lambda s: Minimum(s["column"], where=s.get("where")),
        "Maximum": lambda s: Maximum(s["column"], where=s.get("where")),
        "StandardDeviation": lambda s: StandardDeviation(
            s["column"], where=s.get("where")
        ),
        "MinLength": lambda s: MinLength(s["column"]),
        "MaxLength": lambda s: MaxLength(s["column"]),
        "CountDistinct": lambda s: CountDistinct(s["columns"]),
        "Distinctness": lambda s: Distinctness(s["columns"]),
        "Uniqueness": lambda s: Uniqueness(s["columns"]),
        "UniqueValueRatio": lambda s: UniqueValueRatio(s["columns"]),
        "Entropy": lambda s: Entropy(s["column"]),
        "Compliance": lambda s: Compliance(
            s["instance"], s["predicate"], where=s.get("where")
        ),
        "PatternMatch": lambda s: PatternMatch(
            s["column"], s["pattern"]
        ),
        "Correlation": lambda s: Correlation(s["first"], s["second"]),
        "RatioOfSums": lambda s: RatioOfSums(s["first"], s["second"]),
        "MutualInformation": lambda s: MutualInformation(s["columns"]),
        "ApproxCountDistinct": lambda s: ApproxCountDistinct(
            s["column"]
        ),
        "DataType": lambda s: DataType(s["column"]),
    }
    return kinds[spec["type"]](spec)


def cases():
    """(fixture_name, analyzer_spec) in a stable order."""
    c = []

    def add(fixture, **spec):
        c.append((fixture, spec))

    # nulls_basic — null handling of every aggregate family
    for t in (
        "Size", "Completeness", "Mean", "Sum", "Minimum", "Maximum",
        "StandardDeviation", "ApproxCountDistinct",
    ):
        add("nulls_basic", type=t, column="x")
    add("nulls_basic", type="Completeness", column="s")
    add("nulls_basic", type="CountDistinct", columns=["s"])
    add("nulls_basic", type="Distinctness", columns=["s"])
    add("nulls_basic", type="Uniqueness", columns=["s"])
    add("nulls_basic", type="UniqueValueRatio", columns=["s"])
    add("nulls_basic", type="Entropy", column="s")
    add("nulls_basic", type="Correlation", first="x", second="k")
    add(
        "nulls_basic",
        type="Compliance",
        instance="x big",
        predicate="x >= 3",
    )
    # COUNT(col) vs COUNT(*): Size counts ROWS under where;
    # Compliance's denominator is kept rows, null predicate rows
    # count as non-compliant (SQL: NULL condition -> not true)
    add("count_col_vs_star", type="Size")
    add("count_col_vs_star", type="Size", where="grp = 'a'")
    add("count_col_vs_star", type="Completeness", column="x")
    add(
        "count_col_vs_star",
        type="Completeness",
        column="x",
        where="grp = 'a'",
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="x pos",
        predicate="x > 0",
    )
    add("count_col_vs_star", type="Mean", column="x", where="grp = 'b'")
    # NaN values
    for t in ("Mean", "Sum", "Minimum", "Maximum", "Completeness"):
        add("nan_values", type=t, column="x")
    add("nan_values", type="CountDistinct", columns=["x"])
    # -0.0
    for t in ("Minimum", "Maximum", "Sum", "Mean"):
        add("neg_zero", type=t, column="x")
    add("neg_zero", type="CountDistinct", columns=["x"])
    add("neg_zero", type="Distinctness", columns=["x"])
    # second-moment degenerate shapes: constant column (zero variance
    # -> Spark's corr yields NaN as a SUCCESSFUL value), zero-sum
    # denominator, exact linear dependence (exactly 1.0 — sqrt of the
    # product, not product of sqrts), and MI of identical /
    # independent pairs
    add(
        "moments_edge", type="Correlation", first="const", second="lin"
    )
    add("moments_edge", type="Correlation", first="lin", second="lin")
    add("moments_edge", type="StandardDeviation", column="const")
    add(
        "moments_edge", type="RatioOfSums", first="lin", second="zsum"
    )
    add(
        "moments_edge", type="RatioOfSums", first="zsum", second="lin"
    )
    add(
        "moments_edge",
        type="MutualInformation",
        columns=["g1", "g2"],  # identical partitions: MI = H = ln 2
    )
    add(
        "moments_edge",
        type="MutualInformation",
        columns=["g1", "g3"],  # independent partitions: MI = 0
    )
    add("neg_zero_dict", type="CountDistinct", columns=["x"])
    add("neg_zero_dict", type="Distinctness", columns=["x"])
    add("neg_zero_dict", type="Minimum", column="x")
    # all-NaN column: min/max both NaN (NaN ranks above +inf), never
    # +inf (the identity must not leak; ADVICE via r4 code review)
    for t in ("Minimum", "Maximum", "Mean", "Completeness"):
        add("all_nan", type=t, column="x")
    # empty table
    for t in (
        "Size", "Completeness", "Mean", "Sum", "Minimum", "Maximum",
        "StandardDeviation", "ApproxCountDistinct",
    ):
        add("empty", type=t, column="x")
    add("empty", type="CountDistinct", columns=["s"])
    add("empty", type="Distinctness", columns=["s"])
    add("empty", type="Entropy", column="s")
    add("empty", type="MinLength", column="s")
    # single row
    for t in (
        "Size", "Mean", "StandardDeviation", "Minimum", "Maximum",
    ):
        add("single_row", type=t, column="x")
    add("single_row", type="Uniqueness", columns=["s"])
    add("single_row", type="MinLength", column="s")
    add("single_row", type="MaxLength", column="s")
    # all-null column
    for t in (
        "Completeness", "Mean", "Sum", "Minimum", "Maximum",
        "StandardDeviation", "ApproxCountDistinct",
    ):
        add("all_null", type=t, column="x")
    add("all_null", type="CountDistinct", columns=["s"])
    add("all_null", type="Distinctness", columns=["s"])
    add("all_null", type="MinLength", column="s")
    # strings: empty string vs null lengths; pattern over nulls
    add("strings", type="MinLength", column="s")
    add("strings", type="MaxLength", column="s")
    add("strings", type="PatternMatch", column="s", pattern="^ab")
    add("strings", type="Completeness", column="s")
    add("strings", type="DataType", column="s")
    # SQL three-valued logic, frozen as goldens (a predicate-compiler
    # regression must not silently shift Compliance values):
    # rows: x = [1.0, NULL, 3.0, NULL], grp = a a b b
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="null-cmp",
        predicate="x > 0",  # NULL rows are not compliant
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="null-or",
        predicate="x > 0 OR grp = 'a'",  # TRUE OR NULL = TRUE
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="null-and-false",
        predicate="x > 99 AND grp = 'zz'",  # FALSE AND NULL = FALSE
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="div-zero",
        predicate="x / (x - x) > 0",  # division by zero -> NULL
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="in-null",
        predicate="x IN (1, NULL)",  # match TRUE, else NULL
    )
    add(
        "count_col_vs_star",
        type="Compliance",
        instance="is-null",
        predicate="x IS NULL",
    )
    add(
        "strings",
        type="Compliance",
        instance="like-null",
        predicate="s LIKE 'ab%'",  # null rows not compliant
    )
    add(
        "strings",
        type="Compliance",
        instance="len-empty",
        predicate="LENGTH(s) = 0",  # empty string is NOT null
    )
    return c


# ---------------------------------------------------------------------------
# exact value encoding
# ---------------------------------------------------------------------------


def encode_value(v: Any) -> Any:
    """JSON-exact encoding: NaN/±inf as tagged strings; -0.0 kept
    distinct from 0.0 via the sign bit; Distributions as dicts."""
    if hasattr(v, "values") and hasattr(v, "number_of_bins"):
        return {
            "__distribution__": {
                k: [dv.absolute, encode_value(dv.ratio)]
                for k, dv in sorted(v.values.items())
            }
        }
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        if math.isnan(f):
            return "__nan__"
        if math.isinf(f):
            return "__inf__" if f > 0 else "__-inf__"
        if f == 0.0 and math.copysign(1.0, f) < 0:
            return "__-0.0__"
        return f
    return v


def run_case(dataset, spec) -> Dict[str, Any]:
    """Execute one case; returns the JSON-ready outcome dict."""
    from deequ_tpu.analyzers import AnalysisRunner

    analyzer = build_analyzer(spec)
    ctx = AnalysisRunner.do_analysis_run(dataset, [analyzer])
    metric = ctx.metric(analyzer)
    if metric.value.is_success:
        return {
            "success": True,
            "value": encode_value(metric.value.get()),
        }
    exc = metric.value.exception  # property on Failure
    # unwrap the wrapper to the ROOT cause type: the wrapper class is
    # an implementation detail; the root type is the pinned semantic
    cause = exc
    while cause.__cause__ is not None:
        cause = cause.__cause__
    return {"success": False, "error": type(cause).__name__}
