"""tools/staticcheck: framework, the four AST analyzers, the migrated
token rules, and the whole-repo tier-1 gate.

Per-rule fixtures follow one pattern: a PLANTED violation the analyzer
must catch, and its corrected twin it must stay silent on — so every
rule's detection logic is pinned against both false negatives and the
obvious false positive.
"""

import json
import os
import textwrap

import pytest

from tools.staticcheck import run_analyzers, summarize, to_json, unwaived
from tools.staticcheck.__main__ import main as cli_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, rel, source):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return rel


def _rules_found(tmp_path, rule=None):
    findings = unwaived(run_analyzers(str(tmp_path)))
    if rule is None:
        return findings
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------------
# the tier-1 gate: the shipped tree is clean
# --------------------------------------------------------------------------


class TestRepoGate:
    def test_repo_has_zero_unwaived_findings(self):
        """The staticcheck analogue of test_telemetry's lint gate: every
        finding on the real tree is either fixed or carries a reasoned
        waiver. New code that trips a rule fails HERE."""
        findings = unwaived(run_analyzers(REPO_ROOT))
        assert findings == [], "\n" + "\n".join(
            f.render() for f in findings
        )

    def test_cli_exits_zero_on_repo(self, capsys):
        assert cli_main([REPO_ROOT]) == 0
        out = capsys.readouterr().out
        assert "staticcheck: 0 finding(s)" in out

    def test_repo_waivers_all_carry_reasons(self):
        findings = run_analyzers(REPO_ROOT)
        waived = [f for f in findings if f.waived]
        assert waived, "expected the documented waiver sites to register"
        assert all(f.waive_reason for f in waived)


# --------------------------------------------------------------------------
# lock-discipline / lock-order
# --------------------------------------------------------------------------


LOCK_VIOLATION = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def peek(self):
            return self._n
"""

LOCK_CORRECTED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def peek(self):
            with self._lock:
                return self._n
"""


class TestLockDiscipline:
    def test_catches_unlocked_read_of_protected_attr(self, tmp_path):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        found = _rules_found(tmp_path, "lock-discipline")
        assert len(found) == 1
        assert found[0].symbol == "_n"
        assert "peek" in found[0].message

    def test_silent_on_corrected_twin(self, tmp_path):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_CORRECTED)
        assert _rules_found(tmp_path, "lock-discipline") == []

    def test_locked_suffix_methods_are_lock_scope(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def _take_locked(self):
                    return self._items.pop()
            """,
        )
        assert _rules_found(tmp_path, "lock-discipline") == []

    def test_container_mutation_counts_as_write(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def drain(self):
                    self._items.clear()
            """,
        )
        found = _rules_found(tmp_path, "lock-discipline")
        assert len(found) == 1 and found[0].symbol == "_items"

    def test_init_writes_do_not_flag(self, tmp_path):
        # __init__ publishes before any concurrency exists
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_CORRECTED)
        assert _rules_found(tmp_path, "lock-discipline") == []


LOCK_ORDER_CYCLE = """
    import threading

    class Alpha:
        def __init__(self, beta: "Beta"):
            self._lock = threading.Lock()
            self._x = 0
            self._beta = beta

        def advance(self):
            with self._lock:
                self._x += 1
                self._beta.poke()

        def poke(self):
            with self._lock:
                self._x += 1

    class Beta:
        def __init__(self, alpha: Alpha):
            self._lock = threading.Lock()
            self._y = 0
            self._alpha = alpha

        def advance(self):
            with self._lock:
                self._y += 1
                self._alpha.poke()

        def poke(self):
            with self._lock:
                self._y += 1
"""

LOCK_ORDER_DAG = """
    import threading

    class Alpha:
        def __init__(self, beta: "Beta"):
            self._lock = threading.Lock()
            self._x = 0
            self._beta = beta

        def advance(self):
            with self._lock:
                self._x += 1
                self._beta.poke()

    class Beta:
        def __init__(self):
            self._lock = threading.Lock()
            self._y = 0

        def poke(self):
            with self._lock:
                self._y += 1
"""


class TestLockOrder:
    def test_catches_cross_class_acquisition_cycle(self, tmp_path):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_ORDER_CYCLE)
        found = _rules_found(tmp_path, "lock-order")
        assert len(found) >= 1
        assert "Alpha" in found[0].message and "Beta" in found[0].message

    def test_silent_on_one_directional_dag(self, tmp_path):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_ORDER_DAG)
        assert _rules_found(tmp_path, "lock-order") == []


# --------------------------------------------------------------------------
# interrupt-safety
# --------------------------------------------------------------------------


class TestInterruptSafety:
    def test_catches_swallowing_bare_except(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def run(step):
                try:
                    step()
                except:
                    pass
            """,
        )
        found = _rules_found(tmp_path, "interrupt-swallow")
        assert len(found) == 1

    def test_catches_swallowing_base_exception(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def run(step):
                try:
                    step()
                except BaseException:
                    return None
            """,
        )
        assert len(_rules_found(tmp_path, "interrupt-swallow")) == 1

    def test_silent_when_handler_reraises(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def run(step, log):
                try:
                    step()
                except BaseException:
                    log("interrupted")
                    raise
            """,
        )
        assert _rules_found(tmp_path, "interrupt-swallow") == []

    def test_catches_named_interrupt_without_reraise(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            from deequ_tpu.engine.deadline import ScanInterrupted

            def run(step):
                try:
                    step()
                except ScanInterrupted:
                    return "partial"
            """,
        )
        found = _rules_found(tmp_path, "interrupt-named")
        assert len(found) == 1 and found[0].symbol == "ScanInterrupted"

    def test_silent_on_named_interrupt_with_reraise(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            from deequ_tpu.engine.deadline import ScanInterrupted

            def run(step, checkpoint):
                try:
                    step()
                except ScanInterrupted:
                    checkpoint()
                    raise
            """,
        )
        assert _rules_found(tmp_path, "interrupt-named") == []

    def test_silent_on_plain_except_exception(self, tmp_path):
        # the tunnel exists so that except Exception is SAFE
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            def run(step):
                try:
                    step()
                except Exception:
                    return None
            """,
        )
        assert _rules_found(tmp_path) == []


# --------------------------------------------------------------------------
# trace-hazard
# --------------------------------------------------------------------------


class TestTraceHazard:
    def test_catches_host_coercion_in_jitted_function(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                return float(jnp.sum(x))
            """,
        )
        found = _rules_found(tmp_path, "trace-hazard")
        assert len(found) == 1 and found[0].symbol == "float"

    def test_silent_on_corrected_twin(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                return jnp.sum(x).astype(jnp.float32)
            """,
        )
        assert _rules_found(tmp_path, "trace-hazard") == []

    def test_catches_np_call_on_traced_value(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import numpy as np
            import jax.numpy as jnp

            def step(x):
                y = jnp.abs(x)
                return np.cumsum(y)
            """,
        )
        found = _rules_found(tmp_path, "trace-hazard")
        assert len(found) == 1 and found[0].symbol == "np.cumsum"

    def test_catches_python_if_on_traced_operand(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import jax.numpy as jnp

            def step(x):
                if jnp.any(x > 0):
                    return x
                return -x
            """,
        )
        found = _rules_found(tmp_path, "trace-hazard")
        assert len(found) == 1 and found[0].symbol == "if"

    def test_dtype_dispatch_if_is_static_and_legal(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import jax.numpy as jnp

            def step(x):
                if jnp.issubdtype(x.dtype, jnp.floating):
                    return jnp.nan_to_num(x)
                return x
            """,
        )
        assert _rules_found(tmp_path, "trace-hazard") == []

    def test_traced_set_propagates_through_scan_step(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            from jax import lax

            def _fold(carry, item):
                return carry + item.item(), None

            def run(items, init):
                return lax.scan(_fold, init, items)
            """,
        )
        found = _rules_found(tmp_path, "trace-hazard")
        assert len(found) == 1 and found[0].symbol == "item"

    def test_host_only_module_is_untouched(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import numpy as np

            def fold(parts):
                return float(np.sum(np.asarray(parts)))
            """,
        )
        assert _rules_found(tmp_path, "trace-hazard") == []


# --------------------------------------------------------------------------
# plan-key discipline
# --------------------------------------------------------------------------


PLANKEY_VIOLATION = """
    from deequ_tpu import config

    def _plan_cache_key(ops):
        return tuple(op.cache_token for op in ops)

    def prepare_scan(dataset, ops):
        opts = config.options()
        size = opts.batch_size
        return (_plan_cache_key(ops), size)
"""

PLANKEY_CORRECTED = """
    from deequ_tpu import config

    PLAN_KEY_COVERED_CONFIG = {
        "batch_size": "traces are shape-specialized per batch geometry",
    }

    def _plan_cache_key(ops):
        return tuple(op.cache_token for op in ops)

    def prepare_scan(dataset, ops):
        opts = config.options()
        size = opts.batch_size
        return (_plan_cache_key(ops), size)
"""


class TestPlanKey:
    def test_catches_unkeyed_config_read(self, tmp_path):
        _write(tmp_path, "deequ_tpu/engine/myscan.py", PLANKEY_VIOLATION)
        found = _rules_found(tmp_path, "plan-key")
        assert len(found) == 1 and found[0].symbol == "batch_size"

    def test_silent_when_covered_constant_documents_it(self, tmp_path):
        _write(tmp_path, "deequ_tpu/engine/myscan.py", PLANKEY_CORRECTED)
        assert _rules_found(tmp_path, "plan-key") == []

    def test_silent_when_key_itself_reads_the_attr(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/myscan.py",
            """
            from deequ_tpu import config

            def _plan_cache_key(ops):
                return (tuple(ops), config.options().batch_size)

            def prepare_scan(dataset, ops):
                size = config.options().batch_size
                return (_plan_cache_key(ops), size)
            """,
        )
        assert _rules_found(tmp_path, "plan-key") == []

    def test_reaches_reads_through_helper_calls(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/myscan.py",
            """
            from deequ_tpu import config

            def _plan_cache_key(ops):
                return tuple(ops)

            def _resolve_engine():
                return config.options().engine

            def prepare_scan(dataset, ops):
                eng = _resolve_engine()
                return (_plan_cache_key(ops), eng)
            """,
        )
        found = _rules_found(tmp_path, "plan-key")
        assert len(found) == 1 and found[0].symbol == "engine"

    def test_execute_path_reads_are_out_of_scope(self, tmp_path):
        # config reads OUTSIDE the prepare_scan closure don't flag —
        # they affect execution, not the trace the key guards
        _write(
            tmp_path,
            "deequ_tpu/engine/myscan.py",
            """
            from deequ_tpu import config

            def _plan_cache_key(ops):
                return tuple(ops)

            def prepare_scan(dataset, ops):
                return _plan_cache_key(ops)

            def execute_plan(plan):
                return config.options().scan_retry
            """,
        )
        assert _rules_found(tmp_path, "plan-key") == []


# --------------------------------------------------------------------------
# waivers
# --------------------------------------------------------------------------


class TestWaivers:
    def test_trailing_waiver_suppresses_named_rule(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def peek(self):
                    return self._n  # lint-ok: lock-discipline: snapshot
            """,
        )
        findings = run_analyzers(str(tmp_path))
        assert unwaived(findings) == []
        waived = [f for f in findings if f.waived]
        assert len(waived) == 1 and waived[0].waive_reason == "snapshot"

    def test_standalone_waiver_covers_next_code_line(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def peek(self):
                    # lint-ok: lock-discipline: monitoring snapshot
                    return self._n
            """,
        )
        assert unwaived(run_analyzers(str(tmp_path))) == []

    def test_waiver_for_other_rule_does_not_suppress(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/service/fixture.py",
            """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def peek(self):
                    return self._n  # lint-ok: trace-hazard: wrong rule
            """,
        )
        assert len(_rules_found(tmp_path, "lock-discipline")) == 1

    def test_legacy_sync_ok_maps_to_sync_discipline(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            """
            import jax

            def drain(state):
                return jax.device_get(state)  # sync-ok: checkpoint drain
            """,
        )
        findings = run_analyzers(str(tmp_path))
        sync = [f for f in findings if f.rule == "sync-discipline"]
        assert len(sync) == 1 and sync[0].waived
        assert sync[0].waive_reason == "checkpoint drain"


# --------------------------------------------------------------------------
# the tokenize regression (satellite 1) + shim compat
# --------------------------------------------------------------------------


class TestMalformedFiles:
    def test_unparseable_fixture_degrades_to_findings(self, tmp_path):
        """The TokenizeError regression: an unterminated triple quote
        raises tokenize.TokenError; the old scanner referenced the
        nonexistent tokenize.TokenizeError and died with
        AttributeError on first contact."""
        _write(
            tmp_path,
            "deequ_tpu/engine/broken.py",
            'x = """unterminated\n',
        )
        findings = run_analyzers(str(tmp_path))  # must not raise
        rules = {f.rule for f in findings}
        assert "tokenize-error" in rules
        assert "parse-error" in rules

    def test_shim_reports_legacy_tokenize_error_tuple(self, tmp_path):
        from tools.telemetry_lint import find_violations

        _write(
            tmp_path,
            "deequ_tpu/engine/broken.py",
            'x = """unterminated\n',
        )
        assert find_violations(str(tmp_path)) == [
            ("deequ_tpu/engine/broken.py", 0, "<tokenize error>")
        ]

    def test_shim_delegates_to_framework(self, tmp_path):
        """The shim's tuples are exactly the framework's unwaived
        token-rule findings."""
        from tools.telemetry_lint import TOKEN_RULES, find_violations

        _write(
            tmp_path,
            "deequ_tpu/service/rogue.py",
            "import time\nnow = time.monotonic()\n",
        )
        tuples = find_violations(str(tmp_path))
        findings = unwaived(
            run_analyzers(str(tmp_path), rules=list(TOKEN_RULES))
        )
        assert tuples == [(f.path, f.line, f.symbol) for f in findings]
        assert ("deequ_tpu/service/rogue.py", 2, "monotonic") in tuples


# --------------------------------------------------------------------------
# wire-discipline
# --------------------------------------------------------------------------


WIRE_DATA_PUT = """
    import jax
    import numpy as np

    def ship(batch):
        return jax.device_put(np.asarray(batch))
"""

WIRE_DATA_HOST_ONLY = """
    import numpy as np

    def ship(batch):
        return np.ascontiguousarray(batch)
"""

WIRE_LOOPED_NARROWING = """
    from deequ_tpu.data.table import narrow_codes

    def stream(batches, dict_sizes):
        for b, n in zip(batches, dict_sizes):
            yield narrow_codes(b, n)
"""

WIRE_ONCE_PER_RUN_NARROWING = """
    from deequ_tpu.data.table import narrow_codes

    def plan(column, dict_size):
        codes = narrow_codes(column, dict_size)
        return [codes[i] for i in range(len(codes))]
"""


class TestWireDiscipline:
    def test_catches_device_put_in_data_layer(self, tmp_path):
        _write(tmp_path, "deequ_tpu/data/rogue.py", WIRE_DATA_PUT)
        found = _rules_found(tmp_path, "wire-discipline")
        assert len(found) == 1
        assert found[0].symbol == "jax.device_put"
        assert "data layer" in found[0].message

    def test_catches_jit_in_data_layer(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/data/rogue.py",
            """
            import jax

            def compile_helper(fn):
                return jax.jit(fn)
            """,
        )
        found = _rules_found(tmp_path, "wire-discipline")
        assert len(found) == 1
        assert found[0].symbol == "jax.jit"

    def test_silent_on_host_only_data_module(self, tmp_path):
        _write(tmp_path, "deequ_tpu/data/clean.py", WIRE_DATA_HOST_ONLY)
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_device_put_outside_data_layer_is_fine(self, tmp_path):
        """The engine owns device placement; the rule must not leak
        beyond deequ_tpu/data/."""
        _write(tmp_path, "deequ_tpu/engine/mover.py", WIRE_DATA_PUT)
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_catches_narrowing_call_inside_loop(self, tmp_path):
        _write(
            tmp_path, "deequ_tpu/data/table.py", WIRE_LOOPED_NARROWING
        )
        found = _rules_found(tmp_path, "wire-discipline")
        assert len(found) == 1
        assert found[0].symbol == "narrow_codes"
        assert "fixed-layout" in found[0].message

    def test_silent_on_once_per_run_narrowing(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/data/table.py",
            WIRE_ONCE_PER_RUN_NARROWING,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_narrowing_in_loop_outside_wire_path_is_fine(self, tmp_path):
        """Only the wire-path modules carry the fixed-layout contract;
        a test helper looping over widths must not trip the gate."""
        _write(
            tmp_path,
            "deequ_tpu/sketches/widths.py",
            WIRE_LOOPED_NARROWING,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_waiver_silences_with_reason(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/data/rogue.py",
            """
            import jax
            import numpy as np

            def ship(batch):
                # lint-ok: wire-discipline: fixture exercising waivers
                return jax.device_put(np.asarray(batch))
            """,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []
        findings = run_analyzers(str(tmp_path))
        waived = [f for f in findings if f.waived]
        assert len(waived) == 1
        assert waived[0].waive_reason == "fixture exercising waivers"


# --------------------------------------------------------------------------
# wire-discipline: egress extension (rules 3 and 4)
# --------------------------------------------------------------------------


EGRESS_HOARDING_CONSUME = """
    class Writer:
        def __init__(self):
            self._rows = []

        def consume(self, bits, valid):
            self._rows.append((bits, valid))
"""

EGRESS_FLUSHING_CONSUME = """
    class Writer:
        def __init__(self, spool):
            self._spool = spool

        def consume(self, bits, valid):
            self._spool.write(bits)
            self._spool.flush()
"""

EGRESS_EMITTING_CONSUME = """
    class Writer:
        def consume(self, bits, valid):
            self._pending.append(valid)
            self._emit_span(bits, valid)
"""


class TestWireDisciplineEgress:
    def test_catches_device_put_in_egress_writer(self, tmp_path):
        _write(tmp_path, "deequ_tpu/egress/writer.py", WIRE_DATA_PUT)
        found = _rules_found(tmp_path, "wire-discipline")
        assert len(found) == 1
        assert found[0].symbol == "jax.device_put"
        assert "egress" in found[0].message

    def test_plan_module_is_the_device_half(self, tmp_path):
        """egress/plan.py builds the on-device bit-pack planes; jit and
        device calls there are the design, not a violation."""
        _write(tmp_path, "deequ_tpu/egress/plan.py", WIRE_DATA_PUT)
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_catches_unflushed_consume_buffering(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/egress/writer.py",
            EGRESS_HOARDING_CONSUME,
        )
        found = _rules_found(tmp_path, "wire-discipline")
        assert len(found) == 1
        assert found[0].symbol == "consume"
        assert "flush per scan fold" in found[0].message

    def test_silent_when_consume_writes_through(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/egress/writer.py",
            EGRESS_FLUSHING_CONSUME,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_emit_helper_counts_as_write_through(self, tmp_path):
        """The direct (non-spool) consume path flushes via _emit —
        the heuristic must recognize it, or the real writer trips."""
        _write(
            tmp_path,
            "deequ_tpu/egress/writer.py",
            EGRESS_EMITTING_CONSUME,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_buffering_outside_consume_is_fine(self, tmp_path):
        """Bounded accumulation elsewhere (e.g. the pending-failure
        list, refreshed per degradation record) is legitimate; only
        the per-fold consume path carries the flush contract."""
        _write(
            tmp_path,
            "deequ_tpu/egress/writer.py",
            """
            class Writer:
                def refresh_failures(self, record):
                    self._pending.append(record)
            """,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_consume_buffering_outside_egress_is_fine(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/collector.py",
            EGRESS_HOARDING_CONSUME,
        )
        assert _rules_found(tmp_path, "wire-discipline") == []

    def test_real_egress_package_is_clean(self):
        findings = [
            f
            for f in unwaived(
                run_analyzers(str(REPO_ROOT), rules=["wire-discipline"])
            )
            if f.path.startswith("deequ_tpu/egress/")
        ]
        assert findings == []


# --------------------------------------------------------------------------
# CLI / JSON artifact
# --------------------------------------------------------------------------


class TestCli:
    def test_exit_one_and_listing_on_violation(self, tmp_path, capsys):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        assert cli_main([str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "[lock-discipline]" in out
        assert "staticcheck: 1 finding(s)" in out

    def test_json_artifact_is_machine_readable(self, tmp_path, capsys):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        assert cli_main([str(tmp_path), "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["summary"]["unwaived"] == 1
        assert payload["summary"]["by_rule"] == {"lock-discipline": 1}
        (finding,) = payload["findings"]
        assert finding["rule"] == "lock-discipline"
        assert finding["path"] == "deequ_tpu/service/fixture.py"
        assert finding["line"] > 0

    def test_rules_flag_narrows_the_run(self, tmp_path, capsys):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        assert cli_main([str(tmp_path), "--rules", "trace-hazard"]) == 0
        assert cli_main([str(tmp_path), "--rules", "lock-discipline"]) == 1

    def test_list_rules_prints_catalog(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in (
            "lock-discipline",
            "lock-order",
            "interrupt-swallow",
            "interrupt-named",
            "trace-hazard",
            "plan-key",
            "sync-discipline",
            "wire-discipline",
        ):
            assert f"{rule}:" in out

    def test_nonexistent_root_is_an_error_not_a_clean_pass(self, tmp_path):
        with pytest.raises(SystemExit) as excinfo:
            cli_main([str(tmp_path / "no-such-dir")])
        assert excinfo.value.code == 2

    def test_summarize_matches_json_summary(self, tmp_path):
        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        findings = run_analyzers(str(tmp_path))
        blob = json.loads(to_json(findings, str(tmp_path)))
        assert blob["summary"] == summarize(findings)


class TestObsReport:
    def test_staticcheck_summary_line(self):
        from tools.obs_report import render_staticcheck

        line = render_staticcheck(REPO_ROOT)
        assert line.startswith("staticcheck: 0 finding(s), ")
        assert line.endswith("(clean)")

    def test_staticcheck_flag_without_path(self, capsys):
        from tools.obs_report import main as report_main

        assert report_main(["--staticcheck"]) == 0
        assert capsys.readouterr().out.startswith("staticcheck:")

    def test_staticcheck_line_reports_failing_tree(self, tmp_path):
        from tools.obs_report import render_staticcheck

        _write(tmp_path, "deequ_tpu/service/fixture.py", LOCK_VIOLATION)
        line = render_staticcheck(str(tmp_path))
        assert line.startswith("staticcheck: 1 finding(s)")
        assert "FAILING" in line


# --------------------------------------------------------------------------
# thread-discipline (r10 ingest pool)
# --------------------------------------------------------------------------


THREAD_STRAY = """
    import threading

    def sketch_in_background(fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        return t
"""

THREAD_UNREGISTERED = """
    import threading

    def spawn_worker(fn):
        worker = threading.Thread(target=fn, daemon=True)
        worker.start()
        return worker
"""

THREAD_REGISTERED_WRAPPED = """
    import threading

    from deequ_tpu.engine.ingest import register_ingest_thread

    def spawn_worker(fn):
        worker = register_ingest_thread(
            threading.Thread(target=fn, daemon=True)
        )
        worker.start()
        return worker
"""

THREAD_REGISTERED_BY_NAME = """
    import threading

    from deequ_tpu.engine.ingest import register_ingest_thread

    class Pool:
        def spawn(self, fn):
            self._worker = threading.Thread(target=fn, daemon=True)
            register_ingest_thread(self._worker)
            self._worker.start()
"""

THREAD_WAIVED = """
    import threading

    def spawn_watchdog(fn):
        # lint-ok: thread-discipline: joined-with-timeout in stop()
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        return t
"""

QUEUE_UNBOUNDED = """
    import queue

    def make_channel():
        return queue.Queue()
"""

QUEUE_BOUNDED = """
    import queue

    def make_channel(depth):
        return queue.Queue(maxsize=8)
"""

QUEUE_SIMPLE = """
    from queue import SimpleQueue

    def make_channel():
        return SimpleQueue()
"""


class TestThreadDiscipline:
    SANCTIONED_REL = "deequ_tpu/engine/ingest.py"
    STRAY_REL = "deequ_tpu/analyzers/fixture.py"

    def test_catches_thread_outside_sanctioned_modules(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, THREAD_STRAY)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "outside the sanctioned" in found[0].message

    def test_silent_when_moved_into_sanctioned_module(self, tmp_path):
        # the corrected twin: same spawn, but owned by the ingest
        # module AND registered with the leak probe
        _write(tmp_path, self.SANCTIONED_REL, THREAD_REGISTERED_WRAPPED)
        assert _rules_found(tmp_path, "thread-discipline") == []

    def test_catches_unregistered_thread_in_sanctioned_module(
        self, tmp_path
    ):
        _write(tmp_path, self.SANCTIONED_REL, THREAD_UNREGISTERED)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "register_ingest_thread" in found[0].message

    def test_silent_on_registration_via_assigned_name(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, THREAD_REGISTERED_BY_NAME)
        assert _rules_found(tmp_path, "thread-discipline") == []

    def test_waiver_with_reason_is_honored(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, THREAD_WAIVED)
        assert _rules_found(tmp_path, "thread-discipline") == []
        waived = [
            f
            for f in run_analyzers(str(tmp_path))
            if f.rule == "thread-discipline" and f.waived
        ]
        assert len(waived) == 1
        assert waived[0].waive_reason

    def test_catches_unbounded_queue(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, QUEUE_UNBOUNDED)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "maxsize" in found[0].message

    def test_silent_on_bounded_twin(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, QUEUE_BOUNDED)
        assert _rules_found(tmp_path, "thread-discipline") == []

    def test_simplequeue_always_flagged(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, QUEUE_SIMPLE)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert found[0].symbol == "SimpleQueue"

    def test_queue_outside_sanctioned_modules_flagged(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, QUEUE_BOUNDED)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "outside the sanctioned" in found[0].message


# --------------------------------------------------------------------------
# placement scope (this PR): the elastic placer joins the service
# discipline — thread sanction, injected clocks, admission layering
# --------------------------------------------------------------------------


PLACEMENT_REL = "deequ_tpu/service/placement.py"


class TestPlacementScope:
    def test_placement_is_a_sanctioned_thread_module(self, tmp_path):
        # the same registered spawn that is legal in ingest.py is
        # legal in placement.py — the sanction list covers it
        _write(tmp_path, PLACEMENT_REL, THREAD_REGISTERED_WRAPPED)
        assert _rules_found(tmp_path, "thread-discipline") == []

    def test_sanction_still_demands_registration(self, tmp_path):
        _write(tmp_path, PLACEMENT_REL, THREAD_UNREGISTERED)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "register_ingest_thread" in found[0].message

    def test_unbounded_queue_in_placement_flags(self, tmp_path):
        _write(tmp_path, PLACEMENT_REL, QUEUE_UNBOUNDED)
        found = _rules_found(tmp_path, "thread-discipline")
        assert len(found) == 1
        assert "maxsize" in found[0].message

    def test_wall_clock_wait_in_placement_flags(self, tmp_path):
        # lease waits must ride the injected clock's queue_poll_s —
        # a raw sleep would make DevicePool untestable on fake time
        _write(
            tmp_path,
            PLACEMENT_REL,
            """
            import time

            def wait_for_slice(pool):
                time.sleep(0.25)
            """,
        )
        found = _rules_found(tmp_path, "service-time")
        # both the attribute chain and the bare NAME register
        assert {f.symbol for f in found} == {"time.sleep", "sleep"}

    def test_engine_entry_from_placement_flags(self, tmp_path):
        # the placer hands out leases; executing scans is the
        # scheduler's job, through the runner's admission layer
        _write(
            tmp_path,
            PLACEMENT_REL,
            """
            def place_and_run(engine, plan):
                return engine.execute_plan(plan)
            """,
        )
        found = _rules_found(tmp_path, "service-admission")
        assert [f.symbol for f in found] == ["execute_plan"]

    def test_shipped_placement_module_is_clean(self):
        found = [
            f
            for f in unwaived(run_analyzers(REPO_ROOT))
            if f.path == PLACEMENT_REL
        ]
        assert found == []


# --------------------------------------------------------------------------
# subprocess-discipline
# --------------------------------------------------------------------------


PROC_STRAY_IMPORT = """
    import multiprocessing

    def launch(fn):
        proc = multiprocessing.Process(target=fn)
        proc.start()
        return proc
"""

PROC_CORRECTED = """
    import multiprocessing

    def launch(fn):
        ctx = multiprocessing.get_context("spawn")
        proc = ctx.Process(target=fn)
        proc.start()
        try:
            pass
        finally:
            proc.join()
        return proc.exitcode
"""

PROC_FORK_CONTEXT = """
    import multiprocessing

    def launch(fn):
        ctx = multiprocessing.get_context("fork")
        proc = ctx.Process(target=fn)
        proc.start()
        proc.join()
        return proc.exitcode
"""

PROC_BARE_PROCESS = """
    import multiprocessing

    def launch(fn):
        proc = multiprocessing.Process(target=fn)
        proc.start()
        proc.join()
        return proc.exitcode
"""

PROC_STARTED_NOT_JOINED = """
    import multiprocessing

    def launch(fn):
        ctx = multiprocessing.get_context("spawn")
        proc = ctx.Process(target=fn)
        proc.start()
        return proc
"""

PROC_OS_FORK = """
    import os

    def launch():
        pid = os.fork()
        return pid
"""

PROC_WAIVED = """
    import subprocess  # lint-ok: subprocess-discipline: fixture lifecycle documented here

    def run(cmd):
        return subprocess.run(cmd, check=True)
"""


class TestSubprocessDiscipline:
    SANCTIONED_REL = "deequ_tpu/engine/subproc.py"
    STRAY_REL = "deequ_tpu/analyzers/fixture.py"

    def test_catches_stray_multiprocessing_import(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, PROC_STRAY_IMPORT)
        found = _rules_found(tmp_path, "subprocess-discipline")
        assert len(found) == 1
        assert found[0].symbol == "multiprocessing"
        assert "sanctioned" in found[0].message

    def test_silent_on_corrected_twin_in_sanctioned_module(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, PROC_CORRECTED)
        assert _rules_found(tmp_path, "subprocess-discipline") == []

    def test_catches_fork_context_in_sanctioned_module(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, PROC_FORK_CONTEXT)
        found = _rules_found(tmp_path, "subprocess-discipline")
        assert len(found) == 1
        assert found[0].symbol == "get_context"
        assert "'fork'" in found[0].message

    def test_catches_bare_process_construction(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, PROC_BARE_PROCESS)
        found = _rules_found(tmp_path, "subprocess-discipline")
        assert len(found) == 1
        assert found[0].symbol == "Process"
        assert "get_context('spawn')" in found[0].message

    def test_catches_started_never_joined(self, tmp_path):
        _write(tmp_path, self.SANCTIONED_REL, PROC_STARTED_NOT_JOINED)
        found = _rules_found(tmp_path, "subprocess-discipline")
        assert len(found) == 1
        assert found[0].symbol == "proc"
        assert "zombie" in found[0].message

    def test_catches_os_fork_anywhere(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, PROC_OS_FORK)
        found = _rules_found(tmp_path, "subprocess-discipline")
        assert len(found) == 1
        assert found[0].symbol == "fork"

    def test_waiver_with_reason_is_honored(self, tmp_path):
        _write(tmp_path, self.STRAY_REL, PROC_WAIVED)
        assert _rules_found(tmp_path, "subprocess-discipline") == []
        waived = [
            f
            for f in run_analyzers(str(tmp_path))
            if f.rule == "subprocess-discipline" and f.waived
        ]
        assert len(waived) == 1
        assert waived[0].waive_reason

    def test_shipped_subproc_module_is_clean(self):
        found = [
            f
            for f in unwaived(run_analyzers(REPO_ROOT))
            if f.rule == "subprocess-discipline"
        ]
        assert found == []


# --------------------------------------------------------------------------
# metric-docs: registered metrics <-> docs catalog contract
# --------------------------------------------------------------------------

METRIC_REGISTRATIONS = """
    class _M:
        def counter(self, name):
            return 0

        def gauge(self, name):
            return 0

        def histogram(self, name, value):
            return 0


    def _bump(name, n=1):
        pass


    def work(m, label):
        m.counter("engine.widgets")
        m.gauge("engine.widget_depth")
        m.histogram("engine.widget_wall_s", 0.5)
        m.counter(f"engine.widgets.per_shape.{label}.hits")
        _bump("repository.widget_saves")
        m.counter("not a metric")  # spaces: ignored
        m.counter("plainword")  # no dot: ignored
"""

METRIC_CATALOG_COMPLETE = """\
# Observability

## Metric catalog

| metric | type | meaning |
|---|---|---|
| `engine.widgets` | c | widgets |
| `engine.widget_depth` | g | depth |
| `engine.widget_wall_s` | h | wall |
| `engine.widgets.per_shape.<label>.hits` | c | per-shape family |
| `repository.widget_saves` | c | wrapper-registered |

## Next section

| `engine.outside_catalog` | c | rows outside the section are ignored |
"""


class TestMetricDocs:
    def _docs(self, tmp_path, text):
        docs = tmp_path / "docs"
        docs.mkdir(exist_ok=True)
        (docs / "OBSERVABILITY.md").write_text(text)

    def test_silent_when_catalog_matches(self, tmp_path):
        _write(tmp_path, "deequ_tpu/fixture.py", METRIC_REGISTRATIONS)
        self._docs(tmp_path, METRIC_CATALOG_COMPLETE)
        assert _rules_found(tmp_path, "metric-docs") == []

    def test_catches_registered_but_undocumented(self, tmp_path):
        _write(tmp_path, "deequ_tpu/fixture.py", METRIC_REGISTRATIONS)
        self._docs(
            tmp_path,
            METRIC_CATALOG_COMPLETE.replace(
                "| `engine.widget_depth` | g | depth |\n", ""
            ),
        )
        found = _rules_found(tmp_path, "metric-docs")
        assert len(found) == 1
        assert found[0].symbol == "engine.widget_depth"
        assert found[0].path == "deequ_tpu/fixture.py"
        assert found[0].line > 0

    def test_catches_stale_catalog_row(self, tmp_path):
        _write(tmp_path, "deequ_tpu/fixture.py", METRIC_REGISTRATIONS)
        self._docs(
            tmp_path,
            METRIC_CATALOG_COMPLETE.replace(
                "\n## Next section",
                "| `engine.retired_metric` | c | long gone |\n"
                "\n## Next section",
            ),
        )
        found = _rules_found(tmp_path, "metric-docs")
        assert len(found) == 1
        assert found[0].symbol == "engine.retired_metric"
        assert found[0].path == "docs/OBSERVABILITY.md"

    def test_fstring_holes_match_placeholder_rows(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/fixture.py",
            """
            def work(m, tenant):
                m.counter(f"service.tenant.{tenant}.runs")
            """,
        )
        self._docs(
            tmp_path,
            "## Metric catalog\n\n"
            "| `service.tenant.<tenant>.runs` | c | per-tenant |\n",
        )
        assert _rules_found(tmp_path, "metric-docs") == []

    def test_missing_docs_flags_only_with_registrations(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/fixture.py",
            """
            def work(m):
                m.counter("engine.widgets")
            """,
        )
        found = _rules_found(tmp_path, "metric-docs")
        assert len(found) == 1
        assert "missing" in found[0].message

    def test_silent_on_fixture_roots_without_metrics(self, tmp_path):
        _write(tmp_path, "deequ_tpu/fixture.py", "x = 1\n")
        assert _rules_found(tmp_path, "metric-docs") == []

    def test_shipped_tree_catalog_is_in_sync(self):
        found = [
            f
            for f in unwaived(run_analyzers(REPO_ROOT))
            if f.rule == "metric-docs"
        ]
        assert found == []


# --------------------------------------------------------------------------
# preempt-discipline: no requeue/revoke without checkpoint evidence
# --------------------------------------------------------------------------

PREEMPT_REQUEUE_UNGUARDED = """
class Scheduler:
    def finish(self, ticket, outcome):
        self.queue.requeue(ticket)
"""

PREEMPT_REVOKE_UNGUARDED = """
class Scheduler:
    def release(self, lease, group):
        self.placer.revoke(lease, run_ids=[])
"""

PREEMPT_CORRECTED = """
from deequ_tpu.service.preempt import preempt_checkpoint_evidence

class Scheduler:
    def finish(self, ticket, outcome):
        evidence = preempt_checkpoint_evidence(ticket, outcome)
        if evidence is None:
            return False
        self.queue.requeue(ticket)
        return True

    def release(self, lease, group):
        preempted = [
            t for t in group
            if preempt_checkpoint_evidence(t) is not None
        ]
        if preempted:
            self.placer.revoke(lease, run_ids=preempted)
"""

PREEMPT_NESTED_SCOPE = """
from deequ_tpu.service.preempt import preempt_checkpoint_evidence

class Scheduler:
    def finish(self, ticket, outcome):
        preempt_checkpoint_evidence(ticket, outcome)

        def later():
            # the nested scope never established its own evidence
            self.queue.requeue(ticket)

        return later
"""

PREEMPT_BARE_NAME = """
def requeue(ticket):
    return ticket

def finish(ticket):
    requeue(ticket)
"""


class TestPreemptDiscipline:
    SCOPED_REL = "deequ_tpu/service/fixture.py"

    def test_catches_unguarded_requeue(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, PREEMPT_REQUEUE_UNGUARDED)
        found = _rules_found(tmp_path, "preempt-discipline")
        assert len(found) == 1
        assert found[0].symbol == "requeue"
        assert "preempt_checkpoint_evidence" in found[0].message

    def test_catches_unguarded_revoke(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, PREEMPT_REVOKE_UNGUARDED)
        found = _rules_found(tmp_path, "preempt-discipline")
        assert len(found) == 1
        assert found[0].symbol == "revoke"

    def test_silent_on_corrected_twin(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, PREEMPT_CORRECTED)
        assert _rules_found(tmp_path, "preempt-discipline") == []

    def test_nested_function_needs_its_own_evidence(self, tmp_path):
        # the enclosing scope's evidence call does not license a
        # requeue inside a nested function: deferred execution escapes
        # the cancel -> evidence -> requeue ordering
        _write(tmp_path, self.SCOPED_REL, PREEMPT_NESTED_SCOPE)
        found = _rules_found(tmp_path, "preempt-discipline")
        assert len(found) == 1
        assert found[0].symbol == "requeue"

    def test_out_of_scope_module_is_silent(self, tmp_path):
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            PREEMPT_REQUEUE_UNGUARDED,
        )
        assert _rules_found(tmp_path, "preempt-discipline") == []

    def test_bare_name_call_is_not_the_queue(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, PREEMPT_BARE_NAME)
        assert _rules_found(tmp_path, "preempt-discipline") == []

    def test_shipped_tree_is_clean(self):
        found = [
            f
            for f in unwaived(run_analyzers(REPO_ROOT))
            if f.rule == "preempt-discipline"
        ]
        assert found == []


# --------------------------------------------------------------------------
# egress-durability: no cursor construction without a durable flush
# --------------------------------------------------------------------------

EGRESS_CURSOR_UNGUARDED = """
from deequ_tpu.io.state_provider import EgressCursor

class Writer:
    def checkpoint(self):
        # planted violation: the cursor is minted before anything was
        # made durable — a crash here makes resume drop rows
        return EgressCursor(
            last_durably_flushed_span_seq=self.seq,
            rows_emitted_clean=self.rows_clean,
            rows_emitted_quarantined=self.rows_quarantined,
            plane_spool_offset=0,
        )
"""

EGRESS_CURSOR_CORRECTED = """
import os

from deequ_tpu.io.state_provider import EgressCursor

class Writer:
    def checkpoint(self):
        self._finalize_open_segment()
        return EgressCursor(
            last_durably_flushed_span_seq=self.seq,
            rows_emitted_clean=self.rows_clean,
            rows_emitted_quarantined=self.rows_quarantined,
            plane_spool_offset=0,
        )

    def checkpoint_spool(self):
        os.fsync(self._spool.fileno())
        return EgressCursor(
            last_durably_flushed_span_seq=-1,
            rows_emitted_clean=0,
            rows_emitted_quarantined=0,
            plane_spool_offset=self._spool.tell(),
        )
"""

EGRESS_SCANCURSOR_UNGUARDED = """
from deequ_tpu.io.state_provider import ScanCursor

def save_cursor(ckpt, batch_index):
    ckpt.save(ScanCursor(batch_index, 0, "fp", 104))
"""

EGRESS_NESTED_SCOPE = """
from deequ_tpu.io.state_provider import EgressCursor

class Writer:
    def checkpoint(self):
        self.flush_durable()

        def later():
            # the nested scope never flushed anything itself
            return EgressCursor(
                last_durably_flushed_span_seq=0,
                rows_emitted_clean=0,
                rows_emitted_quarantined=0,
                plane_spool_offset=0,
            )

        return later
"""


class TestEgressDurability:
    SCOPED_REL = "deequ_tpu/egress/fixture.py"

    def test_catches_unguarded_cursor(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, EGRESS_CURSOR_UNGUARDED)
        found = _rules_found(tmp_path, "egress-durability")
        assert len(found) == 1
        assert found[0].symbol == "EgressCursor"
        assert "durable-flush" in found[0].message

    def test_catches_unguarded_scan_cursor(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, EGRESS_SCANCURSOR_UNGUARDED)
        found = _rules_found(tmp_path, "egress-durability")
        assert len(found) == 1
        assert found[0].symbol == "ScanCursor"

    def test_silent_on_corrected_twin(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, EGRESS_CURSOR_CORRECTED)
        assert _rules_found(tmp_path, "egress-durability") == []

    def test_nested_function_needs_its_own_flush(self, tmp_path):
        _write(tmp_path, self.SCOPED_REL, EGRESS_NESTED_SCOPE)
        found = _rules_found(tmp_path, "egress-durability")
        assert len(found) == 1
        assert found[0].symbol == "EgressCursor"

    def test_out_of_scope_module_is_silent(self, tmp_path):
        # the engine's own ScanCursor assembly has its flush on the
        # writer side; the rule scopes to the egress package only
        _write(
            tmp_path,
            "deequ_tpu/engine/fixture.py",
            EGRESS_CURSOR_UNGUARDED,
        )
        assert _rules_found(tmp_path, "egress-durability") == []

    def test_shipped_tree_is_clean(self):
        found = [
            f
            for f in unwaived(run_analyzers(REPO_ROOT))
            if f.rule == "egress-durability"
        ]
        assert found == []
