"""Differential execution-path tests: the SAME analyzer set over the
SAME data must produce equal metrics through every engine path —
resident (chunk-pipelined device cache), streaming (bit-packed batches,
no cache), and the 8-virtual-device mesh. This is the engine-level
analogue of the reference's local-vs-cluster equivalence assumption."""

import numpy as np
import pytest

from deequ_tpu import Dataset, config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxCountDistinct,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.engine import AnalysisEngine


def _mixed_dataset(seed: int, n: int = 40_000) -> Dataset:
    rng = np.random.default_rng(seed)
    x = rng.normal(5.0, 2.0, n).astype(object)
    x[:: rng.integers(5, 20)] = None
    return Dataset.from_pydict(
        {
            "x": list(x),
            "y": list(rng.normal(-1.0, 1.0, n)),
            "k": list(rng.integers(0, n // 2, n, dtype=np.int64)),
            "s": list(
                np.array(["red", "green", "blue", "17", ""])[
                    rng.integers(0, 5, n)
                ]
            ),
        }
    )


def _analyzers():
    return [
        Mean("x"),
        Sum("y"),
        Minimum("x"),
        Maximum("y"),
        StandardDeviation("x"),
        Completeness("x"),
        Correlation("x", "y"),
        Compliance("pos", "x > 5"),
        MinLength("s"),
        MaxLength("s"),
        DataType("s"),
        ApproxCountDistinct("k"),
        CountDistinct("k"),
        Uniqueness("k"),
    ]


def _values(ctx, analyzers):
    out = {}
    for a in analyzers:
        v = ctx.metric(a).value
        assert v.is_success, (a, v)
        value = v.get()
        out[a] = value if isinstance(value, float) else str(value)
    return out


@pytest.mark.parametrize("seed", [3, 11])
def test_resident_streaming_mesh_agree(seed, cpu_mesh):
    data_factory = lambda: _mixed_dataset(seed)  # noqa: E731
    analyzers = _analyzers()

    resident = _values(
        AnalysisRunner.do_analysis_run(data_factory(), analyzers),
        analyzers,
    )
    with config.configure(device_cache_bytes=0, batch_size=4_096):
        streaming = _values(
            AnalysisRunner.do_analysis_run(data_factory(), analyzers),
            analyzers,
        )
    meshed = _values(
        AnalysisRunner.do_analysis_run(
            data_factory(),
            analyzers,
            engine=AnalysisEngine(mesh=cpu_mesh, batch_size=8_192),
        ),
        analyzers,
    )
    for a in analyzers:
        for other, name in ((streaming, "streaming"), (meshed, "mesh")):
            if isinstance(resident[a], float):
                assert other[a] == pytest.approx(
                    resident[a], rel=1e-9, abs=1e-12
                ), (a, name)
            else:
                assert other[a] == resident[a], (a, name)
