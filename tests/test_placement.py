"""Elastic device placement (docs/SERVICE.md "Elastic placement"):
pool allocation units on fake devices, slice-size policy, mesh-cache
identity, lease wait/deadline/cancel composition on ``ManualClock``,
the shape-keyed plan cache on the real 8-virtual-device mesh (same
shape over DIFFERENT devices replays one compiled plan), metric
equality across slice sizes, and the service-level composition —
concurrent runs on disjoint slices, a coalesced group sharing one
lease, and the spawn-isolation payload carrying the slice size."""

import threading
import time

import jax
import numpy as np
import pytest

from deequ_tpu.engine.deadline import (
    CancelToken,
    DeadlineExceeded,
    ManualClock,
    RunBudget,
    RunCancelled,
)
from deequ_tpu.service import (
    DevicePool,
    ElasticPlacer,
    MeshCache,
    PlacementPolicy,
    Priority,
    RunRequest,
    RunState,
    VerificationService,
)
from deequ_tpu.telemetry import get_telemetry


def _spin_until(predicate, timeout_s=10.0):
    """Real-time wait for a cross-thread condition (the clocks under
    test are fake; thread scheduling is not)."""
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


def _fake_pool(n=8, clock=None):
    """Pool over plain ints: allocation logic needs no real devices."""
    return DevicePool(devices=list(range(n)), clock=clock or ManualClock())


# --------------------------------------------------------------------------
# DevicePool: buddy-aligned allocation
# --------------------------------------------------------------------------


class TestDevicePool:
    def test_aligned_slices_are_disjoint(self):
        pool = _fake_pool(8)
        start1, devs1 = pool.try_acquire(1)
        start2, devs2 = pool.try_acquire(2)
        start4, devs4 = pool.try_acquire(4)
        assert (start1, devs1) == (0, (0,))
        # the 2-slice may not straddle the half-busy [0,1] block
        assert (start2, devs2) == (2, (2, 3))
        assert (start4, devs4) == (4, (4, 5, 6, 7))
        assert pool.free_count() == 1  # only device 1 left
        assert pool.try_acquire(2) is None

    def test_released_slices_remerge(self):
        pool = _fake_pool(8)
        leases = [pool.try_acquire(1) for _ in range(4)]  # devs 0-3
        assert [s for s, _ in leases] == [0, 1, 2, 3]
        # free 1 and 2: adjacent but straddling the aligned boundary —
        # a 2-slice must NOT use them (it would fragment the pool)
        pool.release(1, 1)
        pool.release(2, 1)
        start, devs = pool.try_acquire(2)
        assert (start, devs) == (4, (4, 5))
        # freeing 0 and 3 re-merges both aligned 2-blocks
        pool.release(0, 1)
        pool.release(3, 1)
        assert pool.try_acquire(2)[0] == 0
        assert pool.try_acquire(2)[0] == 2

    def test_requests_round_up_to_pow2_and_clamp(self):
        pool = _fake_pool(8)
        assert len(pool.try_acquire(3)[1]) == 4
        pool2 = _fake_pool(8)
        assert len(pool2.try_acquire(100)[1]) == 8
        # a 6-device pool grants at most its floor power of two
        pool3 = _fake_pool(6)
        assert pool3.max_slice == 4
        assert len(pool3.try_acquire(8)[1]) == 4

    def test_acquire_blocks_until_release(self):
        pool = _fake_pool(1)
        start, _ = pool.try_acquire(1)
        got = []
        thread = threading.Thread(
            target=lambda: got.append(pool.acquire(1))
        )
        thread.start()
        time.sleep(0.05)
        assert not got  # still waiting: the pool is full
        pool.release(start, 1)
        thread.join(timeout=10)
        assert got and got[0][0] == 0

    def test_deadline_raises_only_when_every_budget_expired(self):
        clock = ManualClock()
        pool = _fake_pool(1, clock=clock)
        pool.try_acquire(1)  # pool full forever
        budgets = [
            RunBudget(deadline_s=1.0, clock=clock),
            RunBudget(deadline_s=10.0, clock=clock),
        ]
        outcome = []

        def waiter():
            try:
                pool.acquire(1, budgets=budgets)
            except BaseException as exc:  # noqa: BLE001 — under test
                outcome.append(exc)

        thread = threading.Thread(target=waiter)
        thread.start()
        clock.advance(2.0)  # one member expired: the group still waits
        time.sleep(0.05)
        assert not outcome
        clock.advance(20.0)  # every member expired
        assert _spin_until(lambda: outcome)
        thread.join(timeout=10)
        assert isinstance(outcome[0], DeadlineExceeded)

    def test_cancel_raises_only_when_every_token_fired(self):
        clock = ManualClock()
        pool = _fake_pool(1, clock=clock)
        pool.try_acquire(1)
        tokens = [CancelToken(), CancelToken()]
        outcome = []

        def waiter():
            try:
                pool.acquire(1, cancels=tokens)
            except BaseException as exc:  # noqa: BLE001 — under test
                outcome.append(exc)

        thread = threading.Thread(target=waiter)
        thread.start()
        tokens[0].cancel("one member gone")
        time.sleep(0.05)
        assert not outcome  # the surviving member keeps the wait alive
        tokens[1].cancel("all members gone")
        assert _spin_until(lambda: outcome)
        thread.join(timeout=10)
        assert isinstance(outcome[0], RunCancelled)


# --------------------------------------------------------------------------
# PlacementPolicy: slice sizing
# --------------------------------------------------------------------------


class TestPlacementPolicy:
    def test_footprint_to_slice_table(self):
        policy = PlacementPolicy(bytes_per_device=512 << 20)
        mb512 = 512 << 20
        assert policy.slice_size(0, 8) == 1  # no estimate -> default
        assert policy.slice_size(1, 8) == 1
        assert policy.slice_size(mb512, 8) == 1
        assert policy.slice_size(mb512 + 1, 8) == 2
        assert policy.slice_size(3 * mb512, 8) == 4  # pow2 round-up
        assert policy.slice_size(100 * mb512, 8) == 8  # pool clamp

    def test_max_devices_floors_to_pow2(self):
        policy = PlacementPolicy(bytes_per_device=1, max_devices=6)
        assert policy.slice_size(1 << 40, 8) == 4

    def test_default_devices_for_unsized_runs(self):
        policy = PlacementPolicy(default_devices=2)
        assert policy.slice_size(0, 8) == 2
        assert policy.slice_size(-1, 8) == 2


# --------------------------------------------------------------------------
# MeshCache: identity + LRU
# --------------------------------------------------------------------------


class TestMeshCache:
    def test_same_slice_returns_same_mesh_object(self):
        cache = MeshCache(cap=4)
        devices = jax.devices()[:2]
        assert cache.mesh_for(devices) is cache.mesh_for(devices)
        assert len(cache) == 1

    def test_lru_evicts_past_cap(self):
        cache = MeshCache(cap=2)
        devices = jax.devices()
        cache.mesh_for(devices[:1])
        cache.mesh_for(devices[1:2])
        cache.mesh_for(devices[2:3])  # evicts devices[:1]
        assert len(cache) == 2
        # jax interns Mesh objects, so eviction is observed via keys
        assert (0,) not in cache._meshes
        assert set(cache._meshes) == {(1,), (2,)}
        cache.mesh_for(devices[1:2])  # touch -> MRU
        cache.mesh_for(devices[3:4])  # evicts (2,), not (1,)
        assert set(cache._meshes) == {(1,), (3,)}


# --------------------------------------------------------------------------
# ElasticPlacer: lease lifecycle, telemetry, affinity
# --------------------------------------------------------------------------


class TestElasticPlacer:
    def _placer(self, **kw):
        clock = kw.pop("clock", ManualClock())
        return ElasticPlacer(
            pool=DevicePool(devices=list(jax.devices()), clock=clock),
            clock=clock,
            **kw,
        )

    def test_place_release_roundtrip_and_telemetry(self):
        tm = get_telemetry()
        placed_before = tm.counter("service.placements").value
        placer = self._placer()
        lease = placer.place(estimated_bytes=1, run_ids=["r1"])
        assert lease.ndev == 1
        assert lease.mesh.shape == {"dp": 1}
        assert placer.snapshot()["active_slices"] == 1
        assert (
            tm.counter("service.placements").value - placed_before == 1
        )
        placer.release(lease)
        placer.release(lease)  # idempotent
        snap = placer.snapshot()
        assert snap["active_slices"] == 0
        assert snap["pool_free"] == snap["pool_total"]

    def test_concurrent_leases_are_disjoint(self):
        placer = self._placer()
        leases = [placer.place(estimated_bytes=1) for _ in range(4)]
        seen = set()
        for lease in leases:
            ids = set(lease.device_ids)
            assert not seen & ids
            seen |= ids
        for lease in leases:
            placer.release(lease)

    def test_shape_affinity_prefers_last_granted_shape(self):
        placer = self._placer(
            policy=PlacementPolicy(bytes_per_device=1 << 20)
        )
        lease = placer.place(
            estimated_bytes=2 << 20, hint=("ds", "plan")
        )
        assert lease.ndev == 2
        placer.release(lease)
        # the same structural hint now lands on 2 devices even with no
        # estimate — its per-shape plan is already compiled
        assert placer.slice_for(0, hint=("ds", "plan")) == 2
        assert placer.slice_for(0, hint=("other", "plan")) == 1


# --------------------------------------------------------------------------
# Shape-keyed plan cache: real engine on the 8-virtual-device host
# --------------------------------------------------------------------------


def _small_dataset(rows=4_000, seed=3):
    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(seed)
    return Dataset.from_pydict(
        {
            "k1": rng.integers(0, 1 << 30, rows, dtype=np.int64),
            "v1": rng.normal(0, 1, rows).astype(np.float32),
        }
    )


ANALYZER_SET = None  # built lazily: analyzers import jax at module init


def _analyzers():
    from deequ_tpu.analyzers import Completeness, Mean, Size, Sum

    return [Size(), Completeness("k1"), Mean("v1"), Sum("v1")]


def _mesh_over(devices):
    from jax.sharding import Mesh

    return Mesh(np.array(list(devices)), ("dp",))


class TestShapeKeyedPlanCache:
    def test_same_shape_different_devices_replays_one_plan(self):
        """The tentpole compile-economics pin: a 2-device slice over
        devices [2,3] must HIT the plan compiled on devices [0,1] —
        the cache key carries the placement SHAPE, not the devices."""
        from deequ_tpu.analyzers import AnalysisRunner
        from deequ_tpu.engine import AnalysisEngine

        tm = get_telemetry()
        devices = jax.devices()
        data = _small_dataset(seed=21)
        AnalysisRunner.do_analysis_run(
            data,
            _analyzers(),
            engine=AnalysisEngine(mesh=_mesh_over(devices[:2])),
        )
        hits_before = tm.counter(
            "engine.plan_cache.per_shape.mesh2.hits"
        ).value
        misses_before = tm.counter(
            "engine.plan_cache.per_shape.mesh2.misses"
        ).value
        data2 = _small_dataset(seed=22)  # fresh handle, same shape
        AnalysisRunner.do_analysis_run(
            data2,
            _analyzers(),
            engine=AnalysisEngine(mesh=_mesh_over(devices[2:4])),
        )
        assert (
            tm.counter(
                "engine.plan_cache.per_shape.mesh2.misses"
            ).value
            == misses_before
        )
        assert (
            tm.counter("engine.plan_cache.per_shape.mesh2.hits").value
            > hits_before
        )

    def test_slice_sizes_agree_on_metrics(self):
        """The same suite on a 1-, 2- and 4-device slice: count-family
        metrics bit-equal, float32 aggregations within reduction-order
        noise (the test_mesh.py equality contract, per slice shape)."""
        from deequ_tpu.analyzers import AnalysisRunner
        from deequ_tpu.engine import AnalysisEngine

        devices = jax.devices()
        data = _small_dataset(seed=23)
        analyzers = _analyzers()
        single = AnalysisRunner.do_analysis_run(
            data, analyzers, engine=AnalysisEngine()
        )
        for ndev in (1, 2, 4):
            sliced = AnalysisRunner.do_analysis_run(
                data,
                analyzers,
                engine=AnalysisEngine(mesh=_mesh_over(devices[:ndev])),
            )
            for a in analyzers:
                want = single.metric(a).value.get()
                got = sliced.metric(a).value.get()
                if a.name in ("Size", "Completeness"):
                    assert got == want, (ndev, a, got, want)
                else:
                    # float32 partial sums reassociate across slices
                    assert got == pytest.approx(want, rel=1e-5), (
                        ndev, a,
                    )


# --------------------------------------------------------------------------
# Service composition: disjoint slices, coalesced groups, isolation
# --------------------------------------------------------------------------


def _factory_seed50():
    return _small_dataset(seed=50)


def _suite(i=0):
    from deequ_tpu import Check, CheckLevel

    return [
        Check(CheckLevel.ERROR, f"suite-{i}")
        .is_complete("k1")
        .is_non_negative("k1")
    ]


class TestServiceElasticComposition:
    def test_concurrent_runs_execute_on_disjoint_slices(self):
        svc = VerificationService(
            workers=4, isolated=False, coalesce=False,
            elastic_placement=True,
        )
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=_suite(i),
                    dataset_key=f"elastic/{i}",
                    dataset_factory=lambda i=i: _small_dataset(
                        seed=30 + i
                    ),
                    priority=Priority.BATCH,
                )
            )
            for i in range(4)
        ]
        svc.start()
        try:
            results = [h.result(timeout=300) for h in handles]
        finally:
            svc.stop(drain=False, timeout=30)
        from deequ_tpu.verification import VerificationSuite

        for i, (h, r) in enumerate(zip(handles, results)):
            assert h.status == RunState.DONE
            assert h.placement is not None
            assert h.placement["ndev"] == 1  # small run -> small slice
            solo = VerificationSuite.do_verification_run(
                _small_dataset(seed=30 + i), _suite(i)
            )
            assert r.status == solo.status
            for (a, m), (wa, wm) in zip(
                sorted(dict(r.metrics).items(), key=lambda kv: str(kv[0])),
                sorted(
                    dict(solo.metrics).items(), key=lambda kv: str(kv[0])
                ),
            ):
                assert str(a) == str(wa)
                assert m.value.get() == wm.value.get(), a
        # the pool is whole again and the snapshot says so
        snap = svc.snapshot()["placement"]
        assert snap["active_slices"] == 0
        assert snap["pool_free"] == snap["pool_total"]

    def test_coalesced_group_shares_one_lease(self):
        tm = get_telemetry()
        placed_before = tm.counter("service.placements").value
        svc = VerificationService(
            workers=2, isolated=False, coalesce=True,
            coalesce_window_s=0.0, elastic_placement=True,
        )
        handles = [
            svc.submit(
                RunRequest(
                    tenant=f"t{i}",
                    checks=_suite(i),
                    dataset_key="elastic/shared",
                    dataset_factory=lambda: _small_dataset(seed=40),
                    priority=Priority.BATCH,
                )
            )
            for i in range(2)
        ]
        svc.start()
        try:
            for h in handles:
                h.result(timeout=300)
        finally:
            svc.stop(drain=False, timeout=30)
        # ONE lease for the whole group, visible on every member
        assert (
            tm.counter("service.placements").value - placed_before == 1
        )
        ids = {tuple(h.placement["device_ids"]) for h in handles}
        assert len(ids) == 1

    def test_lease_deadline_fails_run_not_worker(self):
        """Pool of one device, first run holds it; the second's budget
        expires while waiting for the lease — it FAILS with
        DeadlineExceeded, and the worker survives to serve the next
        run. All on fake time."""
        clock = ManualClock()
        release = threading.Event()

        def execute(ticket):
            release.wait(timeout=30)
            return object()

        placer = ElasticPlacer(
            pool=DevicePool(
                devices=list(jax.devices())[:1], clock=clock
            ),
            clock=clock,
        )
        svc = VerificationService(
            workers=2, interactive_reserve=0, clock=clock,
            execute=execute, placer=placer, coalesce=False,
        )
        first = svc.submit(
            RunRequest(
                tenant="a", checks=_suite(), dataset_key="d/1",
                dataset_factory=lambda: object(),
            )
        )
        second = svc.submit(
            RunRequest(
                tenant="b", checks=_suite(), dataset_key="d/2",
                dataset_factory=lambda: object(), deadline_s=5.0,
            )
        )
        svc.start()
        try:
            assert _spin_until(
                lambda: first.status == RunState.RUNNING
            )
            clock.advance(10.0)  # burns the waiter's budget
            assert _spin_until(
                lambda: second.status == RunState.FAILED
            )
            with pytest.raises(DeadlineExceeded):
                second.result(timeout=0)
            release.set()
            assert _spin_until(
                lambda: first.status == RunState.DONE
            )
        finally:
            release.set()
            svc.stop(drain=False, timeout=30)

    def test_cancel_while_waiting_for_lease(self):
        clock = ManualClock()
        release = threading.Event()

        def execute(ticket):
            release.wait(timeout=30)
            return object()

        placer = ElasticPlacer(
            pool=DevicePool(
                devices=list(jax.devices())[:1], clock=clock
            ),
            clock=clock,
        )
        svc = VerificationService(
            workers=2, interactive_reserve=0, clock=clock,
            execute=execute, placer=placer, coalesce=False,
        )
        first = svc.submit(
            RunRequest(
                tenant="a", checks=_suite(), dataset_key="d/1",
                dataset_factory=lambda: object(),
            )
        )
        second = svc.submit(
            RunRequest(
                tenant="b", checks=_suite(), dataset_key="d/2",
                dataset_factory=lambda: object(),
            )
        )
        svc.start()
        try:
            assert _spin_until(
                lambda: first.status == RunState.RUNNING
            )
            second.cancel("changed my mind")
            assert _spin_until(
                lambda: second.status
                in (RunState.FAILED, RunState.CANCELLED)
            )
            with pytest.raises(RunCancelled):
                second.result(timeout=0)
            release.set()
        finally:
            release.set()
            svc.stop(drain=False, timeout=30)

    def test_isolation_payload_carries_slice_size(self):
        """Crash isolation composes: the lease itself cannot cross the
        spawn boundary, so the payload ships the slice SIZE and the
        child rebuilds an equal-shape mesh over its own devices."""
        from deequ_tpu.service.service import _child_engine

        svc = VerificationService(
            workers=1, isolated=True, coalesce=False,
            elastic_placement=True,
        )
        # build the payload directly from an admitted ticket + lease;
        # the factory must be a picklable module-level function or the
        # payload (correctly) degrades to None
        from deequ_tpu.analyzers import Completeness

        # Check constraints close over lambdas and cannot cross the
        # spawn boundary — analyzer-only requests can (the established
        # isolated-run idiom, see test_coalesce.TestIsolatedCoalescing)
        handle = svc.submit(
            RunRequest(
                tenant="t", checks=(), dataset_key="iso/1",
                required_analyzers=[Completeness("k1")],
                dataset_factory=_factory_seed50,
            )
        )
        ticket = svc.queue.pop(should_stop=lambda: True)
        lease = svc.placer.place(estimated_bytes=1)
        ticket.lease = lease
        try:
            payload = svc._isolation_payload(ticket)
            assert payload["placement_ndev"] == 1
            engine = _child_engine(
                {"placement_ndev": 2, "checkpoint_path": None}
            )
            assert engine is not None
            assert engine.mesh.shape == {"dp": 2}
            assert _child_engine({"placement_ndev": None}) is None
        finally:
            svc.placer.release(lease)
            svc.queue.task_done(ticket)
            handle.cancel("test cleanup")

    def test_service_warmup_covers_every_slice_shape(self, monkeypatch):
        """``warmup()`` on an elastic service warms EVERY pow2 slice
        shape up to the pool max, so a pool-pressure resize never
        compiles in steady state."""
        captured = {}

        def fake_warm_plans(schema, **kwargs):
            captured.update(kwargs)
            return {"tokens": ["tok-a"]}

        import deequ_tpu.service.service as service_mod

        monkeypatch.setattr(
            service_mod,
            "_load_warm_plans",
            lambda: fake_warm_plans,
        )
        svc = VerificationService(
            workers=1, isolated=False, elastic_placement=True
        )
        tokens = svc.warmup({"k1": "integral"})
        assert tokens == ["tok-a"]
        assert captured["mesh_shapes"] == [1, 2, 4, 8]
