"""Queue-driven autoscaling: the control loop over the wait histograms.

The scheduler exposes its knobs (``resize`` for worker count and
interactive reserve; the ``coalesce`` policy attribute for the BATCH
hold-back window) and the telemetry registry already accumulates
per-class queue-wait histograms (``service.queue_wait_s.interactive``,
``.batch``, …). The :class:`AutoscaleController` closes the loop:
every ``interval_s`` it diffs the histogram snapshots against its last
reading (cumulative-bucket deltas -> an approximate interval p99),
reads queue depth and — when the service tracks SLOs — error-budget
burn, and actuates:

- **interactive pressure** (interval p99 over target, or an
  interactive SLO burning) -> one more worker (capped), at least one
  reserved for the INTERACTIVE class;
- **batch starvation** (batch interval p99 dwarfing the coalesce
  window's possible benefit) -> halve the window, so held-back tickets
  stop paying for peers that never arrive;
- **sustained idleness** (no waits observed, empty queue, several
  consecutive intervals — hysteresis against flapping) -> one worker
  down (floored), window restored toward its configured base.

Every actuation increments ``service.autoscale_adjustments`` and emits
an ``autoscale_adjustment`` event naming the knob, both values, and
the reason — the decision trail is replayable from the event log
alone. ``step()`` is synchronous and side-effect-complete so fake-time
tests drive the controller without the thread; the thread is just
``step()`` under an ``Event.wait`` cadence (never ``time.sleep`` —
service-time discipline), and decisions are timed on the injected
clock.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Optional

from deequ_tpu.engine.deadline import MonotonicClock
from deequ_tpu.telemetry import get_telemetry

INTERACTIVE_WAIT = "service.queue_wait_s.interactive"
BATCH_WAIT = "service.queue_wait_s.batch"

#: consecutive quiet intervals before a scale-down — the hysteresis
#: that keeps a bursty workload from sawtoothing the pool
IDLE_ROUNDS_BEFORE_SCALE_DOWN = 3


def interval_p99(
    prev: Optional[Dict[str, Any]], cur: Optional[Dict[str, Any]]
) -> Optional[float]:
    """Approximate p99 of the observations that landed BETWEEN two
    cumulative histogram snapshots: subtract the cumulative bucket
    counts and walk to the first bound covering 99% of the interval's
    observations. None when the interval saw no observations. Beyond
    the top bucket the all-time max is the best available bound."""
    count = (cur["count"] if cur else 0) - (prev["count"] if prev else 0)
    if count <= 0:
        return None
    target = math.ceil(0.99 * count)
    prev_buckets = prev["buckets"] if prev else {}
    for bound, cum in cur["buckets"].items():
        if cum - prev_buckets.get(bound, 0) >= target:
            return float(bound)
    top = cur.get("max")
    return float(top) if top is not None else math.inf


class AutoscaleController:
    """The feedback loop between the queue-wait histograms and the
    scheduler's capacity knobs. One instance per service; inert until
    ``start()`` (or a test calling ``step()`` directly)."""

    def __init__(
        self,
        scheduler: Any,
        clock: Any = None,
        interval_s: float = 10.0,
        min_workers: int = 1,
        max_workers: int = 8,
        target_interactive_p99_s: float = 1.0,
        slo: Optional[Any] = None,
    ):
        self.scheduler = scheduler
        self.clock = clock or MonotonicClock()
        self.interval_s = max(0.01, float(interval_s))
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.target_interactive_p99_s = float(target_interactive_p99_s)
        self.slo = slo
        # the window the operator configured is the ceiling any
        # restore converges back to
        policy = getattr(scheduler, "coalesce", None)
        self._base_window_s = (
            float(policy.window_s) if policy is not None else 0.0
        )
        self._prev: Dict[str, Optional[Dict[str, Any]]] = {}
        # the first step only baselines the cumulative snapshots: the
        # registry may hold hours of pre-controller history, and
        # actuating on an all-time p99 would mis-size the pool at
        # startup for waits nobody is currently experiencing
        self._primed = False
        self._idle_rounds = 0
        self._steps = 0
        self._adjustments = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        # lint-ok: thread-discipline: service-scoped control loop
        # joined in stop(); not part of a scan, so the ingest probe
        # (which tier-1 asserts empty between scans) must not see it
        self._thread = threading.Thread(
            target=self._loop,
            daemon=True,
            name="deequ-tpu-service-autoscale",
        )
        self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self) -> None:
        # Event.wait paces the loop (wakes immediately on stop());
        # REAL cadence even under a fake service clock — the decisions
        # themselves are timed on the injected clock
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — a control-loop bug must
                pass  # never take down the service it steers

    # -- one control decision -----------------------------------------

    def step(self) -> List[Dict[str, Any]]:
        """Read the signals, actuate at most a one-notch change per
        knob, return the adjustments made (empty = steady state)."""
        tm = get_telemetry()
        hists = tm.metrics.snapshot()["histograms"]
        inter_cur = hists.get(INTERACTIVE_WAIT)
        batch_cur = hists.get(BATCH_WAIT)
        inter_p99 = interval_p99(
            self._prev.get(INTERACTIVE_WAIT), inter_cur
        )
        batch_p99 = interval_p99(self._prev.get(BATCH_WAIT), batch_cur)
        self._prev[INTERACTIVE_WAIT] = inter_cur
        self._prev[BATCH_WAIT] = batch_cur
        depth = self.scheduler.queue.depth()
        self._steps += 1
        if not self._primed:
            self._primed = True
            return []

        adjustments: List[Dict[str, Any]] = []
        pressure_reason = self._interactive_pressure(inter_p99)
        if pressure_reason is not None:
            self._idle_rounds = 0
            self._scale_up(adjustments, pressure_reason)
        elif inter_p99 is None and batch_p99 is None and depth == 0:
            self._idle_rounds += 1
            if self._idle_rounds >= IDLE_ROUNDS_BEFORE_SCALE_DOWN:
                self._idle_rounds = 0
                self._scale_down(adjustments)
        else:
            self._idle_rounds = 0
        self._adjust_window(adjustments, batch_p99)

        for adj in adjustments:
            self._adjustments += 1
            tm.counter("service.autoscale_adjustments").inc()
            tm.event("autoscale_adjustment", at=self.clock.now(), **adj)
        return adjustments

    def _interactive_pressure(
        self, inter_p99: Optional[float]
    ) -> Optional[str]:
        """Why the INTERACTIVE class needs more capacity, or None."""
        if (
            inter_p99 is not None
            and inter_p99 > self.target_interactive_p99_s
        ):
            return (
                f"interactive interval p99 ~{inter_p99:g}s over "
                f"target {self.target_interactive_p99_s:g}s"
            )
        if self.slo is not None:
            try:
                classes = self.slo.snapshot().get("classes", {})
            except Exception:  # noqa: BLE001 — advisory signal only
                return None
            burn = (classes.get("interactive") or {}).get("budget_burn")
            if burn is not None and burn > 1.0:
                return f"interactive SLO budget burning at {burn:g}x"
        return None

    # -- actuators ----------------------------------------------------

    def _scale_up(
        self, adjustments: List[Dict[str, Any]], reason: str
    ) -> None:
        workers = self.scheduler.workers
        reserve = self.scheduler.interactive_reserve
        new_workers = min(self.max_workers, workers + 1)
        # under pressure at least one worker must be fenced off for
        # the INTERACTIVE class, or added capacity just grows the
        # batch residency the class is waiting behind
        new_reserve = max(reserve, 1 if new_workers > 1 else 0)
        if new_workers == workers and new_reserve == reserve:
            return
        self.scheduler.resize(
            workers=new_workers, interactive_reserve=new_reserve
        )
        if new_workers != workers:
            adjustments.append(
                {
                    "knob": "workers",
                    "from_value": workers,
                    "to_value": self.scheduler.workers,
                    "reason": reason,
                }
            )
        if self.scheduler.interactive_reserve != reserve:
            adjustments.append(
                {
                    "knob": "interactive_reserve",
                    "from_value": reserve,
                    "to_value": self.scheduler.interactive_reserve,
                    "reason": reason,
                }
            )

    def _scale_down(self, adjustments: List[Dict[str, Any]]) -> None:
        workers = self.scheduler.workers
        if workers <= self.min_workers:
            return
        reserve = self.scheduler.interactive_reserve
        self.scheduler.resize(workers=workers - 1)
        adjustments.append(
            {
                "knob": "workers",
                "from_value": workers,
                "to_value": self.scheduler.workers,
                "reason": (
                    f"{IDLE_ROUNDS_BEFORE_SCALE_DOWN} consecutive idle "
                    f"intervals"
                ),
            }
        )
        if self.scheduler.interactive_reserve != reserve:
            # resize clamps the reserve under the shrunk pool
            adjustments.append(
                {
                    "knob": "interactive_reserve",
                    "from_value": reserve,
                    "to_value": self.scheduler.interactive_reserve,
                    "reason": "clamped under scale-down",
                }
            )

    def _adjust_window(
        self,
        adjustments: List[Dict[str, Any]],
        batch_p99: Optional[float],
    ) -> None:
        """Shrink the coalesce hold-back window while BATCH interval
        p99 dwarfs what waiting for peers could save; restore toward
        the configured base once batch waits subside."""
        policy = getattr(self.scheduler, "coalesce", None)
        if policy is None or self._base_window_s <= 0:
            return
        window = float(policy.window_s)
        new_window = window
        if (
            batch_p99 is not None
            and window > 0
            and batch_p99 > 4.0 * self._base_window_s
        ):
            new_window = window / 2.0
            if new_window < 0.01:
                new_window = 0.0
            reason = (
                f"batch interval p99 ~{batch_p99:g}s dwarfs the "
                f"{self._base_window_s:g}s hold-back window"
            )
        elif (
            window < self._base_window_s
            and (
                batch_p99 is None or batch_p99 <= self._base_window_s
            )
        ):
            new_window = min(
                self._base_window_s, max(0.01, window * 2.0)
            )
            reason = "batch waits subsided; restoring toward base"
        if new_window == window:
            return
        self.scheduler.coalesce = dataclasses.replace(
            policy, window_s=new_window
        )
        get_telemetry().metrics.gauge(
            "service.coalesce_window_s"
        ).set(new_window)
        adjustments.append(
            {
                "knob": "coalesce_window_s",
                "from_value": window,
                "to_value": new_window,
                "reason": reason,
            }
        )

    # -- introspection ------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        policy = getattr(self.scheduler, "coalesce", None)
        return {
            "steps": self._steps,
            "adjustments": self._adjustments,
            "workers": self.scheduler.workers,
            "interactive_reserve": self.scheduler.interactive_reserve,
            "coalesce_window_s": (
                float(policy.window_s) if policy is not None else None
            ),
            "target_interactive_p99_s": self.target_interactive_p99_s,
            "idle_rounds": self._idle_rounds,
        }
