"""Device half of row-level egress: constraint masks inside the fused scan.

``plan_row_sink`` classifies a run's constraints into the same families
``verification/rowlevel.py`` (the differential oracle) defines:

- **scan families** (mask/predicate, pattern, traceable asserted-value):
  per-row pass booleans are ordinary traced expressions over the device
  batch — the SAME batch the metric ops already consume — so they ride
  the fused scan as one extra ``ScanOps`` whose per-batch output is the
  bit-packed ``(planes, B/8)`` uint8 matrix plus a valid-row count,
  fetched through the scan's packed epilogue and folded into the
  :class:`~deequ_tpu.egress.writer.QuarantineWriter` via ``host_fold``;
- **deferred families** (Uniqueness/UniqueValueRatio — global by
  nature — and assertions ``jax.eval_shape`` cannot trace): evaluated
  at finalize by the oracle's own ``_outcome_for``, merged with the
  spooled scan bit planes. The run then honestly reports
  ``engine.data_passes == 2`` — these families need a second look at
  the data, exactly like the one-pass-spill fallback.

The sink op sets ``cache_token=None`` (the explicit uncacheable
opt-out): its closures hold this run's writer and dataset-compiled
predicates, so the engine's cross-run plan cache must never resurrect
it — plan-cache keys for every other op are untouched, and
``merge_plans`` compatibility is moot because the service refuses to
coalesce sink runs (``CoalescePolicy``: the artifact is per-run).

Bit order is little-endian per byte to match the writer's
``np.unpackbits(..., bitorder="little")``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import pad_pow2
from deequ_tpu.analyzers.base import ScanOps
from deequ_tpu.analyzers.basic import (
    Completeness,
    Compliance,
    Maximum,
    MaxLength,
    Minimum,
    MinLength,
    PatternMatch,
)
from deequ_tpu.analyzers.grouping import Uniqueness, UniqueValueRatio
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    ConstraintDecorator,
)
from deequ_tpu.data.table import ColumnRequest, ROW_MASK
from deequ_tpu.egress.writer import EgressReport, QuarantineWriter, RowLevelSink
from deequ_tpu.sql.predicate import compile_predicate
from deequ_tpu.telemetry import get_telemetry

#: a plane function: (device batch, consts) -> per-row pass booleans
PlaneFn = Callable[[Dict[str, jnp.ndarray], Optional[Dict[str, Any]]], jnp.ndarray]

_BIT_WEIGHTS = np.array([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.uint8)


def _assertion_traceable(assertion, dtype) -> bool:
    """True iff the constraint's assertion vectorizes under tracing into
    a per-row boolean (shape-preserving). Assertions that branch on the
    value (``and``/``if``/chained comparisons) raise under tracing and
    fall back to the oracle's per-unique-value path at finalize."""
    try:
        out = jax.eval_shape(
            lambda v: jnp.asarray(assertion(v)),
            jax.ShapeDtypeStruct((4,), dtype),
        )
    except Exception:  # noqa: BLE001 — untraceable, not an error
        return False
    return tuple(out.shape) == (4,)


def _mask_key(column: str) -> str:
    return f"{column}::mask"


@dataclass
class _PlaneSpec:
    """One scan-evaluated outcome column."""

    name: str
    fn: PlaneFn
    requests: Tuple[ColumnRequest, ...]
    #: index into the where-exclusion planes, or None (no filter)
    excl: Optional[int] = None


@dataclass
class _Deferred:
    name: str
    analyzer: Any
    assertion: Any
    where: Optional[str]


class _SinkScanAdapter:
    """Pairs with the sink ScanOps in the runner's ``scan_pairs`` list —
    the same adapter shape ``ScanUnit``/collector adapters use."""

    def __init__(self, requests: Sequence[ColumnRequest]):
        self._requests = tuple(requests)

    def device_requests(self, dataset) -> Tuple[ColumnRequest, ...]:
        return self._requests

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"RowSinkAdapter({len(self._requests)} requests)"


@dataclass
class RowSinkPlan:
    """Everything the run threads through the fused pass for one sink:
    the op that rides the scan, the writer it folds into, and the
    deferred work finalize still owes."""

    sink: RowLevelSink
    writer: QuarantineWriter
    ops: ScanOps
    adapter: _SinkScanAdapter
    scan_names: List[str]
    deferred: List[_Deferred]
    unsupported: Dict[str, str]
    batch_capacity: int  # rows one plane row can hold (B8 * 8)
    scan_failed: bool = False
    _scan_record: Any = None
    _interrupted: bool = False
    _geometry_bound: bool = field(default=False)

    @property
    def scan_pair(self) -> Tuple[_SinkScanAdapter, ScanOps]:
        return (self.adapter, self.ops)

    def bind_scan_geometry(self, scan_plan, data, engine) -> None:
        """Called between ``prepare_scan`` and ``execute_plan``: fixes
        the quarantine granularity (chunk rows resident, batch rows
        streaming) and arms the live degradation probe so failed units
        interleave into the output in source order."""
        from deequ_tpu.engine.scan import CHUNK_BATCHES

        if scan_plan.batch_size > self.batch_capacity:
            raise RuntimeError(
                f"egress planned for batch_size <= {self.batch_capacity} "
                f"but the scan resolved {scan_plan.batch_size}"
            )
        unit_rows = scan_plan.batch_size
        if scan_plan.mode == "resident":
            chunk_batches = min(
                CHUNK_BATCHES, data.num_batches(scan_plan.batch_size)
            )
            unit_rows = chunk_batches * scan_plan.batch_size
        self.writer.bind_geometry(unit_rows, scan_plan.batch_size)
        # mid-scan the live record is active_degradation; folds drained
        # in the scan's epilogue land AFTER the engine merged + cleared
        # it, so fall back to the run-scoped merged record (the runner
        # resets it per run, and this scan is the run's first)
        self.writer.set_degradation_probe(
            lambda: getattr(engine, "active_degradation", None)
            or engine.last_degradation
        )
        # arm the engine's durable-egress hooks for THIS scan only:
        # the checkpoint writer flushes the open span before saving the
        # cursor, and the resume path reconciles the writer with the
        # checkpoint before restarting (engine/scan.py)
        engine.active_egress = self.writer
        self._geometry_bound = True

    def note_scan_complete(self, engine) -> None:
        # later scans in this run (deferred-family fallbacks) must not
        # touch the sink's durable state
        engine.active_egress = None
        self._scan_record = (
            getattr(engine, "active_degradation", None)
            or engine.last_degradation
        )
        self._interrupted = engine.last_interruption is not None

    def mark_scan_failed(self) -> None:
        self.scan_failed = True


def _classify_constraints(checks, data):
    """Walk every check's constraints once, mirroring the oracle's
    family dispatch, and build plane functions for the scan families."""
    planes: List[_PlaneSpec] = []
    deferred: List[_Deferred] = []
    unsupported: Dict[str, str] = {}
    consts: Dict[str, np.ndarray] = {}
    where_planes: List[PlaneFn] = []
    where_index: Dict[str, int] = {}
    where_requests: List[ColumnRequest] = []
    seen: set = set()

    def _where_plane(where: Optional[str]) -> Optional[int]:
        if where is None:
            return None
        if where in where_index:
            return where_index[where]
        pred = compile_predicate(where, data)
        where_requests.extend(pred.requests)
        where_requests.extend(
            ColumnRequest(c, "mask") for c in pred.columns_used
        )

        def excl_fn(batch, _consts, _pred=pred):
            # True for rows EXCLUDED by the filter (oracle: _where_pass)
            return ~_pred.complies(batch)

        where_index[where] = len(where_planes)
        where_planes.append(excl_fn)
        return where_index[where]

    for check in checks:
        for constraint in getattr(check, "constraints", ()):
            inner = (
                constraint.inner
                if isinstance(constraint, ConstraintDecorator)
                else constraint
            )
            if not isinstance(inner, AnalysisBasedConstraint):
                continue
            name = str(constraint)
            if name in seen:
                continue
            analyzer = inner.analyzer
            where = getattr(analyzer, "where", None)
            try:
                spec = _plane_for(
                    analyzer, inner.assertion, where, data, consts,
                    _where_plane,
                )
            except Exception as exc:  # noqa: BLE001 — degrade this
                # constraint only (oracle: row_level_results' per-
                # constraint try/except); the aggregate path already
                # reports the same exception as a FAILURE result
                seen.add(name)
                unsupported[name] = f"{type(exc).__name__}: {exc}"
                continue
            if spec is None:
                continue  # not a row-level-capable family
            seen.add(name)
            if isinstance(spec, _Deferred):
                spec.name = name
                deferred.append(spec)
            else:
                spec.name = name
                planes.append(spec)
    return planes, list(where_index), where_planes, deferred, unsupported, consts, where_requests


def _plane_for(
    analyzer, assertion, where, data, consts, where_plane
):
    """One constraint -> a _PlaneSpec (rides the scan), a _Deferred
    (finalize phase), or None (not row-level capable). Raises to mark
    the constraint unsupported (bad predicate/pattern)."""
    if isinstance(analyzer, (Uniqueness, UniqueValueRatio)):
        # global by nature — always the oracle's two-pass path
        return _Deferred("", analyzer, assertion, where)

    if isinstance(analyzer, Completeness):
        col = analyzer.column
        excl = where_plane(where)

        def fn(batch, _consts, _k=_mask_key(col)):
            return batch[_k]

        return _PlaneSpec("", fn, (ColumnRequest(col, "mask"),), excl)

    if isinstance(analyzer, Compliance):
        pred = compile_predicate(analyzer.predicate, data)
        excl = where_plane(where)
        reqs = tuple(pred.requests) + tuple(
            ColumnRequest(c, "mask") for c in pred.columns_used
        )

        def fn(batch, _consts, _pred=pred):
            return _pred.complies(batch)

        return _PlaneSpec("", fn, reqs, excl)

    if isinstance(analyzer, PatternMatch):
        import re

        col = analyzer.column
        dictionary = data.dictionary(col)
        prog = re.compile(analyzer.pattern)
        lut = np.zeros(max(len(dictionary), 1) + 1, dtype=bool)
        for i, value in enumerate(dictionary):
            if value is not None and prog.search(str(value)):
                lut[i] = True
        null_idx = len(lut) - 1
        key = f"__rl_lut_{len(consts)}"
        consts[key] = pad_pow2(lut)
        excl = where_plane(where)

        def fn(batch, c, _key=key, _null=null_idx, _col=col):
            lut_d = c[_key]
            # codes arrive wire-narrowed (int16 for small dicts); the
            # LUT gather needs int32 lest a >32k dictionary overflow
            codes = batch[f"{_col}::codes"].astype(jnp.int32)
            idx = jnp.where(codes < 0, _null, codes)
            idx = jnp.clip(idx, 0, lut_d.shape[0] - 1)
            return lut_d[idx] & batch[_mask_key(_col)]

        reqs = (ColumnRequest(col, "codes"), ColumnRequest(col, "mask"))
        return _PlaneSpec("", fn, reqs, excl)

    if isinstance(analyzer, (MinLength, MaxLength, Minimum, Maximum)):
        if assertion is None:
            return None
        repr_name = (
            "lengths" if isinstance(analyzer, (MinLength, MaxLength))
            else "values"
        )
        col = analyzer.column
        dtype = data.request_dtype(ColumnRequest(col, repr_name))
        if not _assertion_traceable(assertion, dtype):
            return _Deferred("", analyzer, assertion, where)
        excl = where_plane(where)

        def fn(batch, _consts, _a=assertion, _col=col, _r=repr_name):
            values = batch[f"{_col}::{_r}"]
            passes = jnp.asarray(_a(values)).astype(jnp.bool_)
            # null rows pass (NullBehavior.Ignore): placeholder lanes
            # may compute garbage, the mask overrides them
            return ~batch[_mask_key(_col)] | passes

        reqs = (ColumnRequest(col, repr_name), ColumnRequest(col, "mask"))
        return _PlaneSpec("", fn, reqs, excl)

    return None  # not a row-level family (Size, Mean, ...)


def _build_ops(
    planes: Sequence[_PlaneSpec],
    where_planes: Sequence[PlaneFn],
    consts: Dict[str, np.ndarray],
    b8: int,
    writer: QuarantineWriter,
) -> ScanOps:
    """The sink ScanOps: a fixed-shape ``(n_planes, B/8)`` uint8 state
    (fixed so OOM sub-slice re-dispatches chain through identically
    shaped jits), little-endian bit-packed on device; ``host_fold``
    hands each fold straight to the writer — the packed epilogue is the
    only device->host hop."""
    plane_fns: List[PlaneFn] = [p.fn for p in planes] + list(where_planes)
    n_planes = len(plane_fns)
    total_bits = b8 * 8
    weights = jnp.asarray(_BIT_WEIGHTS)

    def _pack(batch, c):
        if plane_fns:
            m = jnp.stack(
                [
                    jnp.asarray(f(batch, c)).astype(jnp.bool_)
                    for f in plane_fns
                ]
            )
            w = m.shape[1]
            m = jnp.pad(m, ((0, 0), (0, total_bits - w)))
            bits = jnp.sum(
                m.reshape(n_planes, b8, 8).astype(jnp.uint8) * weights,
                axis=-1,
                dtype=jnp.uint8,
            )
        else:
            bits = jnp.zeros((0, b8), dtype=jnp.uint8)
        valid = jnp.sum(batch[ROW_MASK].astype(jnp.int32), dtype=jnp.int32)
        return {"bits": bits, "valid": valid}

    def init():
        return {
            "bits": jnp.zeros((n_planes, b8), dtype=jnp.uint8),
            "valid": jnp.zeros((), dtype=jnp.int32),
        }

    if consts:
        def update(state, batch, c):
            return _pack(batch, c)
    else:
        def update(state, batch):
            return _pack(batch, None)

    def host_fold(acc, out):
        writer.consume(np.asarray(out["bits"]), int(np.asarray(out["valid"])))
        return {
            "spans": acc["spans"] + 1,
            "rows": acc["rows"] + int(np.asarray(out["valid"])),
        }

    return ScanOps(
        init=init,
        update=update,
        merge=lambda a, b: b,
        host_init=lambda: {"spans": 0, "rows": 0},
        host_fold=host_fold,
        consts=dict(consts) if consts else None,
        # explicit opt-out: closures hold this run's writer + dataset-
        # compiled predicates; never resurrect from the plan cache
        cache_token=None,
    )


def plan_row_sink(
    sink: RowLevelSink, checks, data, engine
) -> Optional[RowSinkPlan]:
    """Build the sink's scan rider for one run, or None (and a
    ``no_row_level_constraints`` report) when nothing in the suite is
    row-level capable."""
    (
        planes,
        _where_strings,
        where_planes,
        deferred,
        unsupported,
        consts,
        where_requests,
    ) = _classify_constraints(checks, data)
    if not planes and not deferred:
        sink.report = EgressReport(
            status="no_row_level_constraints",
            rows_total=int(data.num_rows),
            unsupported=unsupported,
        )
        return None
    batch_size = engine._resolve_batch_size(data.num_rows)
    b8 = (int(batch_size) + 7) // 8
    row_columns = list(sink.columns or data.schema.column_names)
    writer = QuarantineWriter(
        sink,
        data,
        scan_names=[p.name for p in planes],
        excl_of=[p.excl for p in planes],
        deferred_names=[d.name for d in deferred],
        plane_shape=(len(planes) + len(where_planes), b8),
        row_columns=row_columns,
    )
    ops = _build_ops(planes, where_planes, consts, b8, writer)
    requests: List[ColumnRequest] = []
    seen_req: set = set()
    for spec in planes:
        for r in spec.requests:
            if r.key not in seen_req:
                seen_req.add(r.key)
                requests.append(r)
    for r in where_requests:
        if r.key not in seen_req:
            seen_req.add(r.key)
            requests.append(r)
    return RowSinkPlan(
        sink=sink,
        writer=writer,
        ops=ops,
        adapter=_SinkScanAdapter(requests),
        scan_names=[p.name for p in planes],
        deferred=list(deferred),
        unsupported=unsupported,
        batch_capacity=b8 * 8,
    )


def finalize_row_sink(plan: RowSinkPlan, data, engine) -> EgressReport:
    """After the fused pass: run the deferred families through the
    oracle, replay the spool if one exists, drain trailing quarantined
    units, close the writers, and stamp ``sink.report``."""
    tm = get_telemetry()
    sink = plan.sink
    writer = plan.writer
    # defensive: the failed-scan path can reach here without
    # note_scan_complete ever running — later scans (the deferred
    # oracle, other runs on this engine) must not see a stale hook
    engine.active_egress = None
    if plan.scan_failed:
        writer.abort()
        report = EgressReport(
            status="aborted",
            rows_total=int(data.num_rows),
            rows_clean=writer.rows_clean,
            rows_quarantined=writer.rows_quarantined,
            bytes_raw=writer.bytes_raw,
            bytes_encoded=writer.bytes_encoded,
            unsupported=dict(plan.unsupported),
        )
        report.manifest_path = writer.write_manifest(report, {})
        tm.event(
            "rowlevel_egress",
            status="aborted",
            tenant=sink.tenant,
            run_id=sink.run_id,
        )
        sink.report = report
        return report

    unsupported = dict(plan.unsupported)
    deferred_outcomes: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
    if plan.deferred:
        from deequ_tpu.verification.rowlevel import (
            _OracleCache,
            _outcome_for,
            _where_pass,
        )

        # the deferred families re-read the source by nature
        # (uniqueness is global; untraceable assertions run per unique
        # value on the host) — the run honestly pays a second pass
        tm.counter("engine.data_passes").inc()
        cache = _OracleCache(data)
        for d in plan.deferred:
            try:
                excluded = _where_pass(d.where, data, cache)
                outcome = _outcome_for(
                    d.analyzer, data, assertion=d.assertion,
                    excluded=excluded, cache=cache,
                )
            except Exception as exc:  # noqa: BLE001 — oracle degrades
                unsupported[d.name] = f"{type(exc).__name__}: {exc}"
                continue
            if outcome is None:
                unsupported[d.name] = (
                    "assertion raised per-value; no row-level column"
                )
                continue
            deferred_outcomes[d.name] = (outcome, excluded)
        # columns the oracle degraded must not appear in the schema
        writer.deferred_names = [
            n for n in writer.deferred_names if n in deferred_outcomes
        ]

    record = plan._scan_record or engine.last_degradation
    if writer.spool_mode:
        writer.replay_spool(deferred_outcomes, record)
    rows_clean, rows_quarantined = writer.finish(
        record, interrupted=plan._interrupted
    )
    constraints = {n: "scan" for n in plan.scan_names}
    constraints.update({n: "deferred" for n in writer.deferred_names})
    report = EgressReport(
        status="interrupted" if plan._interrupted else "complete",
        rows_total=int(data.num_rows),
        rows_clean=rows_clean,
        rows_quarantined=rows_quarantined,
        bytes_raw=writer.bytes_raw,
        bytes_encoded=writer.bytes_encoded,
        constraints=constraints,
        unsupported=unsupported,
        clean_dir=os.path.dirname(writer._paths.get("clean", "")),
        quarantine_dir=os.path.dirname(
            writer._paths.get("quarantine", "")
        ),
    )
    failures = []
    if record is not None:
        for f in getattr(record, "failures", ()):
            failures.append(
                {
                    "batch_index": int(f.batch_index),
                    "rows": int(f.rows),
                    "error_class": str(f.error_class),
                    "attempts": int(f.attempts),
                }
            )
    report.manifest_path = writer.write_manifest(
        report, {"scan_failures": failures}
    )
    tm.event(
        "rowlevel_egress",
        status=report.status,
        rows_clean=rows_clean,
        rows_quarantined=rows_quarantined,
        bytes_raw=report.bytes_raw,
        bytes_encoded=report.bytes_encoded,
        constraints=len(constraints),
        tenant=sink.tenant,
        run_id=sink.run_id,
    )
    sink.report = report
    return report
