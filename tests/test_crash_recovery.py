"""Crash isolation and restart recovery (docs/RESILIENCE.md,
docs/SERVICE.md): subprocess run isolation (engine/subproc.py), the
durable run journal (service/journal.py), service restart recovery, and
load shedding.

The load-bearing differentials here cross a REAL process boundary: a
child hard-crashes (SIGSEGV/SIGKILL via testing/faults.py — no
exception, no unwinding) and the relaunched child must resume from the
durable checkpoint cursor and finish BIT-IDENTICAL to an uninterrupted
run, on the resident, streaming and mesh paths alike. Every child
function in this module is module-level (spawn pickles by reference);
crash-once semantics cross the relaunch boundary via fsync'd token
marker files, never in-memory state. The autouse reap fixture asserts
no test leaves a zombie child behind.
"""

import multiprocessing
import signal
import threading
import time

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine.deadline import ManualClock
from deequ_tpu.engine.resilience import TransientScanError
from deequ_tpu.engine.subproc import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerOpen,
    CircuitBreaker,
    CrashLoopError,
    IsolatedRunner,
    ProcessCrashed,
    checkpoint_progress_probe,
    reset_breakers,
)
from deequ_tpu.service import (
    Priority,
    RunRequest,
    RunState,
    ServiceOverloaded,
    VerificationService,
)
from deequ_tpu.service import service as service_module
from deequ_tpu.service.journal import RunJournal
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.verification.suite import VerificationSuite


@pytest.fixture(autouse=True)
def _reaped_and_reset():
    """Every test must reap its children (no zombies — the contract the
    subprocess-discipline static rule enforces in the product tree) and
    must not leak breaker state into the next test."""
    reset_breakers()
    yield
    assert multiprocessing.active_children() == []
    reset_breakers()


def _table_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.normal(size=n).tolist(),
        "g": (np.arange(n) % 7).tolist(),
    }


def _analyzers():
    return [
        Size(),
        Completeness("a"),
        Mean("a"),
        ApproxQuantile("a", 0.5),
        Uniqueness(["g"]),
    ]


def _checks(n=1000):
    return [
        Check(CheckLevel.ERROR, "crash-recovery")
        .has_size(lambda s, n=n: s == n)
        .is_complete("a")
    ]


def _result_values(result):
    out = []
    for analyzer, metric in result.metrics.items():
        assert metric.value.is_success, (analyzer, metric.value)
        out.append((str(analyzer), metric.value.get()))
    return sorted(out)


# --------------------------------------------------------------------------
# Spawn-child entry points (module level: pickled by reference; the
# child imports this module via the inherited sys.path)
# --------------------------------------------------------------------------


def _child_ok(payload):
    return {"doubled": payload["x"] * 2}


def _child_raise(payload):
    raise ValueError(payload["message"])


def _child_crash(payload):
    from deequ_tpu.testing.faults import hard_crash

    hard_crash(payload.get("signum"))


def _child_sleep(payload):
    time.sleep(payload.get("seconds", 600))


def _scan_child(payload):
    """Run the resilience-suite scan in a child: mode-specific engine,
    optional token-gated hard-crash fault, checkpointer over a durable
    path — exactly the shape ``IsolatedRunner`` relaunches."""
    from deequ_tpu.engine.scan import AnalysisEngine
    from deequ_tpu.io.state_provider import ScanCheckpointer
    from deequ_tpu.testing.faults import FaultInjectingDataset

    engine_kwargs = {}
    if payload["mode"] == "mesh":
        import jax
        from jax.sharding import Mesh

        engine_kwargs["mesh"] = Mesh(
            np.array(jax.devices("cpu")[:8]), ("dp",)
        )
    ds = Dataset.from_pydict(payload["data"])
    if payload.get("crash_at_batch") is not None:
        ds = FaultInjectingDataset(
            ds,
            crash_at_batch=payload["crash_at_batch"],
            crash_token_path=payload["crash_token_path"],
        )
    opts = dict(
        checkpoint_every_batches=3,
        batch_size=104,
        device_cache_bytes=(1 << 30) if payload["mode"] == "resident" else 0,
    )
    with config.configure(**opts):
        ctx = AnalysisRunner.do_analysis_run(
            ds,
            _analyzers(),
            engine=AnalysisEngine(
                checkpointer=ScanCheckpointer(payload["ckpt_path"]),
                **engine_kwargs,
            ),
        )
    out = []
    for analyzer in _analyzers():
        value = ctx.metric(analyzer).value
        assert value.is_success, (analyzer, value)
        out.append((str(analyzer), value.get()))
    return out


def _service_victim(payload):
    """A whole service daemon that dies by SIGKILL mid-run: submits one
    journaled run over a dataset that hard-crashes the PROCESS at batch
    7 — after the write-ahead submitted record, the started record and
    two checkpoint records have landed durably. Never returns."""
    from deequ_tpu.testing.faults import FaultInjectingDataset

    data = payload["data"]
    ds = FaultInjectingDataset(
        Dataset.from_pydict(data),
        crash_at_batch=7,
        crash_signum=signal.SIGKILL,
    )
    svc = VerificationService(
        workers=1, isolated=False, journal_dir=payload["journal_dir"]
    ).start()
    with config.configure(
        checkpoint_every_batches=3, batch_size=104, device_cache_bytes=0
    ):
        handle = svc.submit(
            RunRequest(
                tenant="acme",
                checks=_checks(),
                dataset=ds,
                priority=Priority.STANDARD,
            )
        )
        handle.wait(timeout=120)  # the SIGKILL lands first
    return "unreachable"


# --------------------------------------------------------------------------
# RunJournal
# --------------------------------------------------------------------------


class TestRunJournal:
    def test_round_trip_and_pending_semantics(self, tmp_path):
        journal = RunJournal(str(tmp_path))
        journal.record_submitted(
            "run-1", tenant="acme", priority=1, deadline_s=30.0,
            dataset_key="ds-a",
        )
        journal.record_submitted("run-2", tenant="beta", priority=2,
                                 deadline_s=None, dataset_key="ds-b")
        journal.record_started("run-1", tenant="acme")
        journal.record_checkpoint("run-1", batch_index=6)
        journal.record_checkpoint("run-1", batch_index=9)
        journal.record_terminal("run-2", RunState.DONE)

        records = journal.replay()
        assert [r["type"] for r in records] == [
            "submitted", "submitted", "started", "checkpoint",
            "checkpoint", "terminal",
        ]
        assert [r["seq"] for r in records] == list(range(1, 7))

        pending = journal.pending_runs()
        assert list(pending) == ["run-1"]  # run-2 reached terminal
        entry = pending["run-1"]
        assert entry["tenant"] == "acme"
        assert entry["priority"] == 1
        assert entry["deadline_s"] == 30.0
        assert entry["started"] is True
        # the LATEST checkpoint wins
        assert entry["last_checkpoint"] == {"batch_index": 9}

    def test_torn_tail_truncates_replay(self, tmp_path):
        journal = RunJournal(str(tmp_path))
        journal.record_submitted("run-1", tenant="acme")
        torn_seq = journal.record_started("run-1")
        journal.record_terminal("run-1", RunState.DONE)
        # corrupt the middle record in place: everything after it is
        # untrusted (truncation semantics), so run-1 reads as pending
        rec = tmp_path / f"runlog-{torn_seq:010d}.rec"
        rec.write_bytes(b"deadbeef\n{not json")
        with get_telemetry().run("torn-tail") as cap:
            replayed = RunJournal(str(tmp_path)).replay()
        assert [r["type"] for r in replayed] == ["submitted"]
        truncations = [
            e for e in cap.final["events"]
            if e.get("event") == "journal_truncated"
        ]
        assert len(truncations) == 1
        assert truncations[0]["at_seq"] == torn_seq
        assert list(RunJournal(str(tmp_path)).pending_runs()) == ["run-1"]

    def test_sequence_continues_across_instances(self, tmp_path):
        first = RunJournal(str(tmp_path))
        first.record_submitted("run-1", tenant="acme")
        first.record_started("run-1")
        reopened = RunJournal(str(tmp_path))
        assert reopened.record_checkpoint("run-1", batch_index=3) == 3
        assert [r["seq"] for r in reopened.replay()] == [1, 2, 3]

    def test_compact_drops_terminal_runs(self, tmp_path):
        journal = RunJournal(str(tmp_path))
        journal.record_submitted("run-1", tenant="acme")
        journal.record_submitted("run-2", tenant="acme")
        journal.record_started("run-1")
        journal.record_terminal("run-1", RunState.DONE)
        assert journal.compact() == 3  # run-1's whole story
        assert list(journal.pending_runs()) == ["run-2"]
        # appended records keep climbing past the compacted tail
        assert journal.record_started("run-2") > 4


# --------------------------------------------------------------------------
# IsolatedRunner basics
# --------------------------------------------------------------------------


class TestIsolatedRunner:
    def test_result_crosses_the_pipe(self):
        runner = IsolatedRunner(key="ok", use_breaker=False)
        assert runner.run(_child_ok, {"x": 21}) == {"doubled": 42}

    def test_in_band_exception_passes_through(self):
        """An ordinary exception is NOT a crash: it ships back over the
        pipe and re-raises in the parent, with no relaunch."""
        tm = get_telemetry()
        crashes_before = tm.counter("engine.child_crashes").value
        runner = IsolatedRunner(key="raise", use_breaker=False)
        with pytest.raises(ValueError, match="decode exploded"):
            runner.run(_child_raise, {"message": "decode exploded"})
        assert tm.counter("engine.child_crashes").value == crashes_before

    def test_sigsegv_classified_and_crash_loop_bounded(self):
        tm = get_telemetry()
        crashes_before = tm.counter("engine.child_crashes").value
        relaunches_before = tm.counter("engine.child_relaunches").value
        loops_before = tm.counter("engine.crash_loops").value
        runner = IsolatedRunner(
            key="poison", max_relaunches=2, use_breaker=False
        )
        with pytest.raises(CrashLoopError) as excinfo:
            runner.run(_child_crash, {"signum": signal.SIGSEGV})
        exc = excinfo.value
        assert exc.launches == 2
        assert exc.last_signal == "SIGSEGV"
        assert isinstance(exc.__cause__, ProcessCrashed)
        assert isinstance(exc.__cause__, TransientScanError)
        assert tm.counter("engine.child_crashes").value - crashes_before == 2
        assert (
            tm.counter("engine.child_relaunches").value - relaunches_before
            == 1
        )
        assert tm.counter("engine.crash_loops").value - loops_before == 1

    def test_timeout_terminates_and_classifies(self):
        runner = IsolatedRunner(
            key="hung", max_relaunches=1, timeout_s=10.0, use_breaker=False
        )
        with pytest.raises(CrashLoopError) as excinfo:
            runner.run(_child_sleep, {"seconds": 600})
        assert excinfo.value.last_signal == "timeout"


# --------------------------------------------------------------------------
# Crash → relaunch → bit-identical resume (the differential)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["resident", "streaming", "mesh"])
class TestCrashResumeDifferential:
    def test_crash_then_relaunch_bit_identical(self, mode, tmp_path):
        data = _table_data()
        tm = get_telemetry()
        ref = _scan_child(
            {
                "mode": mode,
                "data": data,
                "ckpt_path": str(tmp_path / "ref-ckpt"),
            }
        )
        ckpt_path = str(tmp_path / "ckpt")
        resumes_before = tm.counter("engine.resumes").value
        crashes_before = tm.counter("engine.child_crashes").value
        crash_resumes_before = tm.counter("engine.crash_resumes").value
        runner = IsolatedRunner(
            key=f"scan:{mode}",
            max_relaunches=3,
            timeout_s=300.0,
            progress_probe=checkpoint_progress_probe(ckpt_path),
            use_breaker=False,
        )
        got = runner.run(
            _scan_child,
            {
                "mode": mode,
                "data": data,
                "ckpt_path": ckpt_path,
                # batch 7 of 10 (104-row batches over 1000 rows), past
                # the cursor the child checkpointed after batch 5
                "crash_at_batch": 7,
                "crash_token_path": str(tmp_path / "crash-token"),
            },
        )
        assert got == ref
        assert tm.counter("engine.child_crashes").value - crashes_before == 1
        assert (
            tm.counter("engine.crash_resumes").value - crash_resumes_before
            == 1
        )
        # the relaunched child's own resume counter folds into the
        # parent's telemetry stream (child summary merge)
        assert tm.counter("engine.resumes").value - resumes_before == 1


# --------------------------------------------------------------------------
# Crash-loop breaker
# --------------------------------------------------------------------------


class TestCrashLoopBreaker:
    def test_loop_opens_fast_fails_then_half_open_probe_closes(self):
        tm = get_telemetry()
        trips_before = tm.counter("engine.breaker_trips").value
        clock = ManualClock()
        breaker = CircuitBreaker(cooldown_s=60.0, clock=clock)
        runner = IsolatedRunner(
            key="plan:poison", max_relaunches=2, breaker=breaker
        )
        with pytest.raises(CrashLoopError):
            runner.run(_child_crash, {"signum": signal.SIGSEGV})
        assert breaker.state == OPEN
        assert tm.counter("engine.breaker_trips").value - trips_before == 1

        # fast-fail while open: no child is spawned at all
        crashes_before = tm.counter("engine.child_crashes").value
        with pytest.raises(BreakerOpen) as excinfo:
            IsolatedRunner(key="plan:poison", breaker=breaker).run(
                _child_ok, {"x": 1}
            )
        assert 0.0 < excinfo.value.retry_after_s <= 60.0
        assert excinfo.value.key == "plan:poison"
        assert tm.counter("engine.child_crashes").value == crashes_before

        # past the cooldown ONE half-open probe is admitted; its
        # success closes the breaker
        clock.advance(61.0)
        probe_runner = IsolatedRunner(key="plan:poison", breaker=breaker)
        assert probe_runner.run(_child_ok, {"x": 2}) == {"doubled": 4}
        assert breaker.state == CLOSED

    def test_half_open_admits_exactly_one_probe(self):
        clock = ManualClock()
        breaker = CircuitBreaker(cooldown_s=30.0, clock=clock)
        breaker.record_crash_loop("k")
        clock.advance(31.0)
        breaker.admit("k")  # the probe slot
        assert breaker.state == HALF_OPEN
        with pytest.raises(BreakerOpen):
            breaker.admit("k")  # concurrent launch during the probe
        breaker.record_success("k")
        assert breaker.state == CLOSED
        breaker.admit("k")  # closed again: free passage

    def test_disabled_by_config(self):
        from deequ_tpu.engine.subproc import breaker_for

        with config.configure(crash_breaker_cooldown_s=0):
            assert breaker_for("any-key") is None


# --------------------------------------------------------------------------
# Service crash-loop flooring (degradation_policy)
# --------------------------------------------------------------------------


def _force_isolation(monkeypatch, svc):
    """Route every run of ``svc`` through the REAL isolated path with a
    crashing child entry: the payload is trivially picklable and the
    module-level crash function replaces ``_isolated_execute`` (looked
    up at call time, pickled by reference to THIS module)."""
    monkeypatch.setattr(
        svc, "_isolation_payload", lambda ticket: {"signum": None}
    )
    monkeypatch.setattr(service_module, "_isolated_execute", _child_crash)


class TestServiceCrashLoopFlooring:
    def _submit_crashing_run(self):
        svc = VerificationService(workers=1, isolated=True)
        svc.start()
        handle = svc.submit(
            RunRequest(
                tenant="acme",
                checks=_checks(),
                dataset=Dataset.from_pydict(_table_data(n=8)),
            )
        )
        return svc, handle

    def test_policy_fail_fails_the_handle(self, monkeypatch):
        with config.configure(
            degradation_policy="fail",
            crash_max_relaunches=1,
            crash_breaker_cooldown_s=0,
        ):
            svc, handle = self._submit_crashing_run()
            _force_isolation(monkeypatch, svc)
            try:
                assert handle.wait(timeout=120)
                assert handle.status == RunState.FAILED
                with pytest.raises(CrashLoopError):
                    handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=10)

    def test_policy_warn_floors_with_provenance(self, monkeypatch):
        with config.configure(
            degradation_policy="warn",
            crash_max_relaunches=1,
            crash_breaker_cooldown_s=0,
        ):
            svc, handle = self._submit_crashing_run()
            _force_isolation(monkeypatch, svc)
            try:
                assert handle.wait(timeout=120)
                assert handle.status == RunState.DONE
                result = handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=10)
        assert result.status == CheckStatus.WARNING
        assert result.metrics == {}
        failure = result.degradation.failures[0]
        assert failure.error_class == "CrashLoopError"
        assert failure.batch_index == -1
        assert failure.attempts >= 1


# --------------------------------------------------------------------------
# Service restart recovery (the journal end-to-end)
# --------------------------------------------------------------------------


class TestServiceRestartRecovery:
    def test_sigkilled_service_recovers_and_resumes(self, tmp_path):
        """The whole daemon dies by SIGKILL mid-run; a fresh service
        over the same journal dir re-admits the run, resumes it from
        the durable checkpoint cursor (content fingerprints match), and
        finishes with the exact metrics of an uninterrupted run."""
        data = _table_data()
        journal_dir = str(tmp_path / "journal")
        victim = IsolatedRunner(
            key="victim", max_relaunches=1, timeout_s=300.0,
            use_breaker=False,
        )
        with pytest.raises(CrashLoopError) as excinfo:
            victim.run(
                _service_victim, {"data": data, "journal_dir": journal_dir}
            )
        assert excinfo.value.last_signal == "SIGKILL"

        # the write-ahead journal survived the kill: submitted +
        # started + checkpoint records, no terminal
        pending = RunJournal(journal_dir).pending_runs()
        assert len(pending) == 1
        (run_id, entry), = pending.items()
        assert entry["started"] is True
        assert entry["last_checkpoint"] is not None

        tm = get_telemetry()
        resumes_before = tm.counter("engine.resumes").value
        recovered_before = tm.counter("service.runs_recovered").value
        with config.configure(
            checkpoint_every_batches=3, batch_size=104, device_cache_bytes=0
        ):
            oracle = VerificationSuite.do_verification_run(
                Dataset.from_pydict(data), _checks()
            )
            svc = VerificationService(
                workers=1, isolated=False, journal_dir=journal_dir
            )
            recovered = svc.recover(
                resolve=lambda rid, e: RunRequest(
                    tenant=e["tenant"],
                    checks=_checks(),
                    dataset=Dataset.from_pydict(data),
                )
            )
            assert [h.run_id for h in recovered] == [run_id]
            assert (
                tm.counter("service.runs_recovered").value
                - recovered_before
                == 1
            )
            svc.start()
            try:
                handle = recovered[0]
                assert handle.wait(timeout=120)
                assert handle.status == RunState.DONE
                result = handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=10)
        # resumed from the DEAD run's cursor, not restarted: the clean
        # dataset's content fingerprint matches the victim's
        assert tm.counter("engine.resumes").value - resumes_before == 1
        assert result.status == CheckStatus.SUCCESS
        assert _result_values(result) == _result_values(oracle)
        # the finished run reached its terminal journal record
        assert RunJournal(journal_dir).pending_runs() == {}

    def test_unresolvable_run_fails_loudly(self, tmp_path):
        journal_dir = str(tmp_path / "journal")
        RunJournal(journal_dir).record_submitted(
            "run-9", tenant="ghost", priority=1, deadline_s=None,
            dataset_key="gone",
        )
        svc = VerificationService(
            workers=1, isolated=False, journal_dir=journal_dir,
            execute=lambda ticket: None,
        )
        assert svc.recover(resolve=lambda rid, e: None) == []
        journal = RunJournal(journal_dir)
        assert journal.pending_runs() == {}
        # a fresh service must not mint run ids that collide with
        # journaled ones
        handle = svc.submit(
            RunRequest(
                tenant="acme",
                checks=[],
                dataset=Dataset.from_pydict({"a": [1.0]}),
            )
        )
        assert int(handle.run_id.rsplit("-", 1)[-1]) > 9


# --------------------------------------------------------------------------
# Load shedding
# --------------------------------------------------------------------------


class TestLoadShedding:
    def test_deep_queue_sheds_batch_not_standard(self):
        release = threading.Event()
        started = threading.Event()

        def _blocking_execute(ticket):
            started.set()
            release.wait(timeout=30)
            return None

        tm = get_telemetry()
        shed_before = tm.counter("service.submissions_shed").value
        svc = VerificationService(
            workers=1,
            execute=_blocking_execute,
            shed_queue_depth=2,
            shed_crash_rate=0,
        )
        svc.start()
        try:
            def _req(priority):
                return RunRequest(
                    tenant="acme",
                    checks=[],
                    dataset=Dataset.from_pydict({"a": [1.0]}),
                    priority=priority,
                )

            svc.submit(_req(Priority.STANDARD))
            assert started.wait(timeout=10)
            svc.submit(_req(Priority.STANDARD))
            svc.submit(_req(Priority.STANDARD))  # queue depth now >= 2
            with pytest.raises(ServiceOverloaded) as excinfo:
                svc.submit(_req(Priority.BATCH))
            assert excinfo.value.retry_after_s >= 0.0
            assert (
                tm.counter("service.submissions_shed").value - shed_before
                == 1
            )
            # INTERACTIVE/STANDARD are never shed
            svc.submit(_req(Priority.STANDARD))
            svc.submit(_req(Priority.INTERACTIVE))
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)

    def test_crash_rate_sheds_until_window_drains(self):
        clock = ManualClock()
        svc = VerificationService(
            workers=1,
            clock=clock,
            execute=lambda ticket: None,
            shed_queue_depth=0,
            shed_crash_rate=2,
            shed_crash_window_s=60.0,
        )

        def _req(priority=Priority.BATCH):
            return RunRequest(
                tenant="acme",
                checks=[],
                dataset=Dataset.from_pydict({"a": [1.0]}),
                priority=priority,
            )

        svc._note_crash()
        svc._note_crash()
        with pytest.raises(ServiceOverloaded) as excinfo:
            svc.submit(_req())
        assert 0.0 < excinfo.value.retry_after_s <= 60.0
        # the window drains on the service clock: old crashes expire
        clock.advance(61.0)
        handle = svc.submit(_req())
        assert handle is not None


# --------------------------------------------------------------------------
# Bench harness (crash-proof rounds: probe + autosize, no spawns here)
# --------------------------------------------------------------------------


class TestBenchHarness:
    def test_probe_host_shape(self):
        import bench

        probe = bench.probe_host()
        assert probe["cpu_count"] >= 1
        assert "mem_available_mb" in probe

    def test_autosize_small_host_caps_streamed_rows(self, monkeypatch):
        import bench

        monkeypatch.delenv("DEEQU_TPU_BENCH_SCALE", raising=False)
        sizing = bench.autosize({"cpu_count": 1, "mem_available_mb": 2048})
        assert sizing["row_scale"] == 0.125
        assert sizing["streaming_row_cap"] == 800_000
        # streamed configs stay under the documented crash threshold
        assert bench._sized(100_000_000, sizing, streamed=True) == 800_000
        # and nothing sizes below the statistical floor
        assert bench._sized(200_000, sizing) == 100_000

    def test_autosize_env_override_wins(self, monkeypatch):
        import bench

        monkeypatch.setenv("DEEQU_TPU_BENCH_SCALE", "1.0")
        sizing = bench.autosize({"cpu_count": 1, "mem_available_mb": 1024})
        assert sizing["row_scale"] == 1.0
        assert sizing["streaming_row_cap"] is None

    def test_registry_covers_child_dispatch(self):
        import bench

        assert "profiler" in bench.CONFIG_REGISTRY
        assert all(callable(fn) for fn in bench.CONFIG_REGISTRY.values())
