"""Row-level schema validation: split a dataset into valid/invalid rows.

Reference: ``src/main/scala/com/amazon/deequ/schema/`` (SURVEY.md §1
L11, §2.5): ``RowLevelSchema`` column definitions (string/int/decimal/
timestamp with nullability, length bounds, regex) and
``RowLevelSchemaValidator.validate(df, schema)`` producing a valid-row
DataFrame (with enforced types) and an invalid-row DataFrame. The
reference builds Spark cast-and-check expressions; here every check is
a vectorized Arrow compute kernel over the raw columns — one boolean
validity mask per definition, AND-ed into the row split. No per-row
Python.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from deequ_tpu.data.table import Dataset


@dataclass(frozen=True)
class ColumnDefinition:
    name: str
    is_nullable: bool = True


@dataclass(frozen=True)
class StringColumnDefinition(ColumnDefinition):
    min_length: Optional[int] = None
    max_length: Optional[int] = None
    matches: Optional[str] = None  # regex


@dataclass(frozen=True)
class IntColumnDefinition(ColumnDefinition):
    min_value: Optional[int] = None
    max_value: Optional[int] = None


@dataclass(frozen=True)
class FractionalColumnDefinition(ColumnDefinition):
    pass


@dataclass(frozen=True)
class DecimalColumnDefinition(ColumnDefinition):
    precision: int = 38
    scale: int = 0


@dataclass(frozen=True)
class TimestampColumnDefinition(ColumnDefinition):
    mask: str = "yyyy-MM-dd HH:mm:ss"  # Java SimpleDateFormat style


class RowLevelSchema:
    """Fluent schema builder (reference: RowLevelSchema case class)."""

    def __init__(self, definitions: Optional[List[ColumnDefinition]] = None):
        self.definitions: List[ColumnDefinition] = list(definitions or [])

    def _add(self, definition: ColumnDefinition) -> "RowLevelSchema":
        return RowLevelSchema(self.definitions + [definition])

    def with_string_column(
        self,
        name: str,
        is_nullable: bool = True,
        min_length: Optional[int] = None,
        max_length: Optional[int] = None,
        matches: Optional[str] = None,
    ) -> "RowLevelSchema":
        return self._add(
            StringColumnDefinition(
                name, is_nullable, min_length, max_length, matches
            )
        )

    def with_int_column(
        self,
        name: str,
        is_nullable: bool = True,
        min_value: Optional[int] = None,
        max_value: Optional[int] = None,
    ) -> "RowLevelSchema":
        return self._add(
            IntColumnDefinition(name, is_nullable, min_value, max_value)
        )

    def with_fractional_column(
        self, name: str, is_nullable: bool = True
    ) -> "RowLevelSchema":
        return self._add(FractionalColumnDefinition(name, is_nullable))

    def with_decimal_column(
        self,
        name: str,
        precision: int = 38,
        scale: int = 0,
        is_nullable: bool = True,
    ) -> "RowLevelSchema":
        return self._add(
            DecimalColumnDefinition(name, is_nullable, precision, scale)
        )

    def with_timestamp_column(
        self,
        name: str,
        mask: str = "yyyy-MM-dd HH:mm:ss",
        is_nullable: bool = True,
    ) -> "RowLevelSchema":
        return self._add(
            TimestampColumnDefinition(name, is_nullable, mask)
        )


@dataclass
class RowLevelSchemaValidationResult:
    valid_rows: Dataset
    num_valid_rows: int
    invalid_rows: Dataset
    num_invalid_rows: int


# at most 18 digits: every 18-digit decimal fits int64, so the regex
# gate guarantees pc.cast(int64) cannot raise on gated values (19-digit
# strings — even the few inside int64 range — classify as invalid)
_INT_RE = r"^\s*[+-]?\d{1,18}\s*$"
_FRACTIONAL_RE = r"^\s*[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?\s*$"

_JAVA_TO_STRPTIME = [
    ("yyyy", "%Y"),
    ("yy", "%y"),
    ("MM", "%m"),
    ("dd", "%d"),
    ("HH", "%H"),
    ("mm", "%M"),
    ("ss", "%S"),
    ("SSS", "%f"),
]


def java_mask_to_strptime(mask: str) -> str:
    out = mask
    for java, c in _JAVA_TO_STRPTIME:
        out = out.replace(java, c)
    return out


def _as_string_array(column: pa.ChunkedArray) -> pa.ChunkedArray:
    if pa.types.is_string(column.type) or pa.types.is_large_string(
        column.type
    ):
        return column
    if pa.types.is_dictionary(column.type):
        return pc.cast(column, pa.string())
    return pc.cast(column, pa.string())


def _nullable_ok(
    valid: pa.ChunkedArray, is_null: pa.ChunkedArray, nullable: bool
) -> pa.ChunkedArray:
    """Combine a non-null-value validity with null policy: nulls are
    valid iff the definition is nullable."""
    if nullable:
        return pc.or_(valid, is_null)
    return pc.and_(valid, pc.invert(is_null))


def _decimal_regex(precision: int, scale: int) -> str:
    int_digits = max(precision - scale, 1)
    if scale > 0:
        return (
            rf"^\s*[+-]?\d{{1,{int_digits}}}(\.\d{{0,{scale}}})?\s*$"
        )
    return rf"^\s*[+-]?\d{{1,{int_digits}}}\s*$"


def _check_column(
    definition: ColumnDefinition, column: pa.ChunkedArray
) -> pa.ChunkedArray:
    """Boolean validity per row for one definition (vectorized)."""
    is_null = column.is_null()
    if isinstance(definition, StringColumnDefinition):
        s = _as_string_array(column)
        valid = pc.true_unless_null(s)
        valid = pc.fill_null(valid, False)
        if definition.min_length is not None:
            valid = pc.and_(
                valid,
                pc.fill_null(
                    pc.greater_equal(
                        pc.utf8_length(s), definition.min_length
                    ),
                    False,
                ),
            )
        if definition.max_length is not None:
            valid = pc.and_(
                valid,
                pc.fill_null(
                    pc.less_equal(pc.utf8_length(s), definition.max_length),
                    False,
                ),
            )
        if definition.matches is not None:
            valid = pc.and_(
                valid,
                pc.fill_null(
                    pc.match_substring_regex(s, definition.matches), False
                ),
            )
    elif isinstance(definition, IntColumnDefinition):
        if pa.types.is_integer(column.type):
            valid = pc.fill_null(pc.true_unless_null(column), False)
            numeric = column
        else:
            s = _as_string_array(column)
            valid = pc.fill_null(
                pc.match_substring_regex(s, _INT_RE), False
            )
            numeric = None
        if definition.min_value is not None or definition.max_value is not None:
            if numeric is None:
                numeric = _parse_numeric(column, pa.int64())
            if definition.min_value is not None:
                valid = pc.and_(
                    valid,
                    pc.fill_null(
                        pc.greater_equal(numeric, definition.min_value),
                        False,
                    ),
                )
            if definition.max_value is not None:
                valid = pc.and_(
                    valid,
                    pc.fill_null(
                        pc.less_equal(numeric, definition.max_value), False
                    ),
                )
    elif isinstance(definition, FractionalColumnDefinition):
        if pa.types.is_floating(column.type) or pa.types.is_integer(
            column.type
        ):
            valid = pc.fill_null(pc.true_unless_null(column), False)
        else:
            s = _as_string_array(column)
            valid = pc.fill_null(
                pc.match_substring_regex(s, _FRACTIONAL_RE), False
            )
    elif isinstance(definition, DecimalColumnDefinition):
        s = _as_string_array(column)
        valid = pc.fill_null(
            pc.match_substring_regex(
                s, _decimal_regex(definition.precision, definition.scale)
            ),
            False,
        )
    elif isinstance(definition, TimestampColumnDefinition):
        if pa.types.is_timestamp(column.type):
            valid = pc.fill_null(pc.true_unless_null(column), False)
        else:
            s = _as_string_array(column)
            parsed = _parse_timestamps(s, definition.mask)
            valid = pc.and_(
                pc.fill_null(pc.true_unless_null(parsed), False),
                pc.invert(pc.fill_null(is_null, False)),
            )
    else:
        raise TypeError(f"unknown column definition {type(definition)}")
    return _nullable_ok(valid, is_null, definition.is_nullable)


def _parse_numeric(column: pa.ChunkedArray, target: pa.DataType):
    """Lenient numeric parse: unparseable -> null (validity is decided
    by the regex mask, not here)."""
    s = _as_string_array(column)
    looks = pc.match_substring_regex(s, _INT_RE)
    masked = pc.if_else(pc.fill_null(looks, False), s, pa.scalar(None, s.type))
    stripped = pc.utf8_trim_whitespace(masked)
    return pc.cast(stripped, target)


def _cast_valid(
    definition: ColumnDefinition, column: pa.ChunkedArray
) -> pa.ChunkedArray:
    """Enforced output type for the valid-row split (reference: the
    valid DataFrame carries the declared types)."""
    if isinstance(definition, IntColumnDefinition):
        if pa.types.is_integer(column.type):
            return pc.cast(column, pa.int64())
        return _parse_numeric(column, pa.int64())
    if isinstance(definition, (FractionalColumnDefinition, DecimalColumnDefinition)):
        if pa.types.is_floating(column.type) or pa.types.is_integer(
            column.type
        ):
            return pc.cast(column, pa.float64())
        s = pc.utf8_trim_whitespace(_as_string_array(column))
        return pc.cast(s, pa.float64(), safe=False)
    if isinstance(definition, TimestampColumnDefinition):
        if pa.types.is_timestamp(column.type):
            return column
        return _parse_timestamps(_as_string_array(column), definition.mask)
    return _as_string_array(column)


def _parse_timestamps(s: pa.ChunkedArray, mask: str) -> pa.ChunkedArray:
    """Vectorized timestamp parse, invalid -> null. pyarrow's strptime
    does not support %f (fractional seconds); masks containing SSS fall
    back to pandas to_datetime, which does."""
    fmt = java_mask_to_strptime(mask)
    if "%f" not in fmt:
        return pc.strptime(s, format=fmt, unit="ms", error_is_null=True)
    import pandas as pd

    parsed = pd.to_datetime(
        s.to_pandas(), format=fmt, errors="coerce"
    )
    return pa.chunked_array([pa.Array.from_pandas(parsed, type=pa.timestamp("ms"))])


class RowLevelSchemaValidator:
    @staticmethod
    def validate(
        data: Dataset, schema: RowLevelSchema
    ) -> RowLevelSchemaValidationResult:
        table = data.table
        n = table.num_rows
        row_valid = pa.chunked_array([pa.array(np.ones(n, dtype=bool))])
        for definition in schema.definitions:
            if definition.name not in table.schema.names:
                raise KeyError(
                    f"schema references unknown column {definition.name!r}"
                )
            col_valid = _check_column(
                definition, table.column(definition.name)
            )
            row_valid = pc.and_(row_valid, pc.fill_null(col_valid, False))

        valid_table = table.filter(row_valid)
        invalid_table = table.filter(pc.invert(row_valid))

        # enforce declared types on the valid split
        arrays = {}
        for name in valid_table.schema.names:
            definition = next(
                (d for d in schema.definitions if d.name == name), None
            )
            column = valid_table.column(name)
            arrays[name] = (
                _cast_valid(definition, column)
                if definition is not None
                else column
            )
        valid_typed = pa.table(arrays)

        return RowLevelSchemaValidationResult(
            valid_rows=Dataset(valid_typed),
            num_valid_rows=valid_typed.num_rows,
            invalid_rows=Dataset(invalid_table),
            num_invalid_rows=invalid_table.num_rows,
        )
