"""Worker pool draining the run queue under priority discipline.

The scheduler owns N executor threads. The first
``interactive_reserve`` of them ONLY ever take INTERACTIVE-class
tickets — that reserve is the anti-starvation mechanism: however long
a BATCH run occupies the general workers, a reserved worker is always
free for the next high-priority short run, so an interactive run's
queue wait is bounded by interactive traffic alone, never by batch
residency (asserted on fake clocks in tests/test_service.py; the
acceptance scenario in examples/verification_service.py).

The ``execute`` callable is injected: the real service passes a
closure that leases the shared dataset and drives
``VerificationSuite.do_verification_run`` through the admission layer;
fake-clock tests pass stubs that advance a ``ManualClock`` instead of
doing work. The scheduler itself therefore never needs real time — its
only blocking wait is ``RunQueue.pop_group``, which polls at the
injected clock's cadence.

Scan coalescing (docs/SERVICE.md "Scan coalescing"): with a
``coalesce`` policy attached and an ``execute_group`` callable
injected, workers pop GROUPS of compatible tickets
(``RunQueue.pop_group`` forms them atomically under the queue lock)
and the group shares one superset scan; every member keeps its own
handle, timeline, events, and terminal transition — the fan-out below
applies the exact same finish semantics per member as a solo run.

Preemption (docs/SERVICE.md "Preemption and autoscaling"): with a
``PreemptionController`` attached, every executing group is registered
as a potential victim and an INTERACTIVE ticket that finds no free
worker (or an exhausted device pool) preempts the youngest solo BATCH
run. The worker owning the victim then routes through
``_requeue_preempted`` instead of the terminal path: checkpoint
evidence extracted, ``preempted`` journal record written, lease
REVOKED rather than released, ticket requeued at its original seq.
Autoscaling rides on the same plumbing: ``resize`` retargets the pool
and workers re-read ``self.workers``/``self.interactive_reserve``
every loop iteration, so scale-down is just a worker noticing its
index is out of range.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from deequ_tpu.engine.deadline import MonotonicClock
from deequ_tpu.service.preempt import preempt_checkpoint_evidence
from deequ_tpu.service.queue import (
    Priority,
    RunQueue,
    RunState,
    RunTicket,
    finish_ticket_trace,
)
from deequ_tpu.telemetry import get_telemetry

QUEUE_WAIT_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0)


class Scheduler:
    """N worker threads popping the queue; ``interactive_reserve`` of
    them restricted to the INTERACTIVE class."""

    def __init__(
        self,
        queue: RunQueue,
        execute: Callable[[RunTicket], Any],
        workers: int = 2,
        interactive_reserve: int = 1,
        clock: Any = None,
        execute_group: Optional[
            Callable[[List[RunTicket]], List[Any]]
        ] = None,
        coalesce: Optional[Any] = None,
        placer: Optional[Any] = None,
        slo_tenants: Optional[Any] = None,
        preemption: Optional[Any] = None,
        on_preempted: Optional[
            Callable[[RunTicket, Any], None]
        ] = None,
        on_resumed: Optional[Callable[[RunTicket], None]] = None,
        fence: Optional[Callable[[], bool]] = None,
    ):
        self.queue = queue
        self.execute = execute
        # fleet epoch fence (service/fleet.py): called before terminal
        # handle transitions; False means this replica's lease epoch
        # was superseded mid-run — the adopter owns these runs now, so
        # their outcomes are DROPPED, not finished (finishing would
        # fire on_terminal journal writes the zombie no longer owns)
        self.fence = fence
        # superset-scan executor: takes the whole group, returns one
        # outcome PER MEMBER in order (a VerificationResult, or an
        # exception instance for a member that failed individually).
        # Without it, groups never form (the policy is ignored).
        self.execute_group = execute_group
        self.coalesce = coalesce if execute_group is not None else None
        # elastic placement (service/placement.py): when wired, a
        # worker leases a device slice for its group BEFORE marking the
        # runs started — lease wait lands inside queue_wait_s and burns
        # the members' budgets, exactly like admission-queue wait
        self.placer = placer
        self.workers = max(1, int(workers))
        # at least one general worker must remain or BATCH/STANDARD
        # work could never run at all
        self.interactive_reserve = min(
            max(0, int(interactive_reserve)), self.workers - 1
        )
        self.clock = clock or MonotonicClock()
        # tenants with an SLO objective get a per-tenant queue-wait
        # histogram (bounded cardinality: only configured tenants)
        self.slo_tenants = frozenset(slo_tenants or ())
        # checkpoint-conserving preemption (service/preempt.py); None
        # (the default) keeps every path below bit-identical to the
        # pre-preemption scheduler
        self.preemption = preemption
        self.on_preempted = on_preempted
        self.on_resumed = on_resumed
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._started = False
        # worker occupancy + interactive capacity-wait accounting
        # (preemption triggers and the batch-defer signal read these)
        self._state_lock = threading.Lock()
        self._busy = 0
        self._capacity_waits = 0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        self._started = True
        self._spawn_to_target()

    def _spawn_to_target(self) -> None:
        """(Re)spawn worker threads so every index < ``self.workers``
        has a live thread. Indices are stable identities: a worker
        whose index falls out of range exits at its next loop check,
        and a later scale-up respawns that index fresh."""
        while len(self._threads) < self.workers:
            self._threads.append(self._spawn(len(self._threads)))
        for i in range(min(self.workers, len(self._threads))):
            if not self._threads[i].is_alive():
                self._threads[i] = self._spawn(i)

    def _spawn(self, index: int) -> threading.Thread:
        reserved = index < self.interactive_reserve
        # lint-ok: thread-discipline: pool workers are joined in
        # Scheduler.stop(); registering them with the scan-scoped
        # ingest probe would trip the between-scans leak assertion
        thread = threading.Thread(
            target=self._worker_loop,
            args=(index,),
            daemon=True,
            name=(
                f"deequ-tpu-service-{'reserve' if reserved else 'exec'}"
                f"-{index}"
            ),
        )
        thread.start()
        return thread

    def resize(
        self,
        workers: Optional[int] = None,
        interactive_reserve: Optional[int] = None,
    ) -> None:
        """Retarget the pool (the autoscaler's actuator — the single
        writer of these targets after construction). Workers re-read
        the targets every loop iteration: scale-up spawns threads
        immediately, scale-down drains — an out-of-range worker
        finishes its current group, then exits at the next pop. The
        targets stay plain ints (atomic assignment; worker reads are
        deliberately unlocked monitoring reads) — only the spawn
        bookkeeping needs the lock."""
        target_workers = (
            self.workers if workers is None else max(1, int(workers))
        )
        target_reserve = (
            self.interactive_reserve
            if interactive_reserve is None
            else max(0, int(interactive_reserve))
        )
        # at least one general worker must remain or BATCH/STANDARD
        # work could never run at all
        self.interactive_reserve = min(
            target_reserve, target_workers - 1
        )
        self.workers = target_workers
        with self._state_lock:
            if self._started and not self._stop.is_set():
                self._spawn_to_target()
        tm = get_telemetry()
        tm.metrics.gauge("service.workers").set(self.workers)
        tm.metrics.gauge("service.interactive_reserve").set(
            self.interactive_reserve
        )

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Stop taking new work and join the workers. Running tickets
        finish (the service cancels them first on a hard stop)."""
        self._stop.set()
        self._started = False
        for thread in self._threads:
            thread.join(timeout=timeout)
        self._threads = [t for t in self._threads if t.is_alive()]

    @property
    def running(self) -> bool:
        return any(t.is_alive() for t in self._threads)

    # -- per-ticket bookkeeping -----------------------------------------

    def _mark_started(self, ticket: RunTicket, group_size: int) -> None:
        tm = get_telemetry()
        handle = ticket.handle
        handle.started_at = self.clock.now()
        wait_s = max(0.0, handle.started_at - ticket.submitted_at)
        tm.metrics.histogram(
            "service.queue_wait_s", buckets=QUEUE_WAIT_BUCKETS
        ).observe(wait_s)
        # per-class split: the coalescing bench's "INTERACTIVE p99
        # unharmed" criterion needs waits attributable by class
        tm.metrics.histogram(
            f"service.queue_wait_s.{Priority.name(handle.priority)}",
            buckets=QUEUE_WAIT_BUCKETS,
        ).observe(wait_s)
        if handle.tenant in self.slo_tenants:
            tm.metrics.histogram(
                f"service.queue_wait_s.tenant.{handle.tenant}",
                buckets=QUEUE_WAIT_BUCKETS,
            ).observe(wait_s)
        if ticket.trace is not None:
            # the wait splits into plain queueing and the coalesce
            # hold-back window (when the policy held this ticket for
            # peers); both are children of the ticket root
            window_s = 0.0
            if ticket.coalesce_held_until > ticket.submitted_at:
                window_s = min(
                    wait_s,
                    ticket.coalesce_held_until - ticket.submitted_at,
                )
            tm.emit_span(
                "queue_wait",
                max(0.0, wait_s - window_s),
                trace=ticket.trace,
                parent_id=ticket.trace.span_id,
                priority=Priority.name(handle.priority),
            )
            if window_s > 0.0:
                tm.emit_span(
                    "coalesce_window",
                    window_s,
                    trace=ticket.trace,
                    parent_id=ticket.trace.span_id,
                    group_size=group_size,
                )
        handle._mark_running()
        tm.event(
            "service_run_started",
            run_id=handle.run_id,
            tenant=handle.tenant,
            priority=Priority.name(handle.priority),
            queue_wait_s=round(wait_s, 6),
            coalesced=group_size > 1,
        )
        if ticket.preemptions > 0:
            # a preempted run starting again IS the resume: the durable
            # cursor (keyed to source fingerprint + plan token, not the
            # slice) picks the scan up past every completed batch
            tm.counter("service.preempt_resumes").inc()
            tm.event(
                "service_run_resumed",
                run_id=handle.run_id,
                tenant=handle.tenant,
                preemptions=ticket.preemptions,
            )
            if self.on_resumed is not None:
                try:
                    self.on_resumed(ticket)
                except Exception:  # noqa: BLE001 — journaling must
                    pass  # never block the resume itself

    def _finish_failed(self, ticket: RunTicket, exc: BaseException) -> None:
        tm = get_telemetry()
        handle = ticket.handle
        handle.finished_at = self.clock.now()
        handle._finish(RunState.FAILED, error=exc)
        tm.counter("service.failed").inc()
        tm.event(
            "service_run_finished",
            run_id=handle.run_id,
            tenant=handle.tenant,
            priority=Priority.name(handle.priority),
            status="failed",
            error=repr(exc),
        )
        finish_ticket_trace(ticket, RunState.FAILED)

    def _finish_result(self, ticket: RunTicket, result: Any) -> None:
        tm = get_telemetry()
        handle = ticket.handle
        handle.finished_at = self.clock.now()
        interruption = getattr(result, "interruption", None)
        cancelled = (
            interruption is not None
            and getattr(interruption, "kind", "") != "deadline"
        )
        handle._finish(
            RunState.CANCELLED if cancelled else RunState.DONE,
            result=result,
        )
        tm.counter("service.completed").inc()
        tm.counter(f"service.tenant.{handle.tenant}.runs").inc()
        tm.event(
            "service_run_finished",
            run_id=handle.run_id,
            tenant=handle.tenant,
            priority=Priority.name(handle.priority),
            status=(
                "cancelled" if cancelled else str(
                    getattr(
                        getattr(result, "status", None), "value", "done"
                    )
                )
            ),
            wall_s=round(handle.finished_at - handle.started_at, 6),
            interrupted=interruption is not None,
        )
        finish_ticket_trace(
            ticket,
            RunState.CANCELLED if cancelled else RunState.DONE,
        )

    def _finish_outcome(self, ticket: RunTicket, outcome: Any) -> None:
        """Apply a per-member group outcome through the same terminal
        semantics as a solo run: exception instances fail the member,
        anything else is its result."""
        if isinstance(outcome, BaseException):
            self._finish_failed(ticket, outcome)
        else:
            self._finish_result(ticket, outcome)

    # -- preemption -----------------------------------------------------

    def note_interactive_demand(self, run_id: str) -> bool:
        """An INTERACTIVE ticket just entered the queue; preempt the
        youngest running solo BATCH group if nothing can serve it —
        every worker busy, or the device pool exhausted. No-op (False)
        without a controller."""
        if self.preemption is None:
            return False
        with self._state_lock:
            free_workers = self.workers - self._busy
        if free_workers > 0 and self._pool_has_room():
            return False
        return self.preemption.preempt_for(run_id)

    def _pool_has_room(self) -> bool:
        if self.placer is None:
            return True
        try:
            return self.placer.pool.free_count() > 0
        except Exception:  # noqa: BLE001 — a placer without a pool
            return True  # cannot signal exhaustion

    def _defer_batch(self) -> bool:
        """True while an INTERACTIVE group is blocked waiting for pool
        capacity: queued BATCH tickets yield by skip (they stay queued,
        untouched) instead of racing it into the pool only to be
        cancel-preempted moments later."""
        # lint-ok: lock-discipline: monitoring read of an int the
        # capacity-wait scopes keep consistent; a stale read only
        # delays/advances a batch pop by one poll tick
        return self._capacity_waits > 0

    def _requeue_preempted(self, ticket: RunTicket, outcome: Any) -> bool:
        """The preemption finish path: if this attempt's outcome is
        checkpoint-bearing cancel evidence (the preempt token fired and
        the engine exited cleanly through its checkpoint path), journal
        the preemption, requeue the ticket at its original seq, and
        report True — the caller skips the terminal transition. Any
        other outcome reports False and takes the normal path: a run
        that completed before the cancel landed just finishes (its
        work is NOT discarded), and a client cancel stays CANCELLED."""
        evidence = preempt_checkpoint_evidence(ticket, outcome)
        if evidence is None:
            return False
        tm = get_telemetry()
        handle = ticket.handle
        if getattr(evidence, "checkpointed", False):
            # conservation credit: batches the durable cursor carries
            # across the preemption (the resume will not re-scan them)
            tm.counter("service.preempted_batches_conserved").inc(
                max(0, int(getattr(evidence, "batch_index", 0)))
            )
        # write-ahead: the journal learns about the preemption BEFORE
        # the ticket re-enters the queue, so a process death in between
        # still recovers the run from the preemption record
        if self.on_preempted is not None:
            try:
                self.on_preempted(ticket, evidence)
            except Exception:  # noqa: BLE001 — journaling must never
                pass  # lose the requeue
        if not self.queue.requeue(ticket):
            # queue closed under us (service stopping): nothing to
            # resume into — apply normal terminal semantics instead
            self._finish_outcome(ticket, outcome)
            return True
        tm.counter("service.preempt_requeues").inc()
        tm.event(
            "service_run_preempted",
            run_id=handle.run_id,
            tenant=handle.tenant,
            priority=Priority.name(handle.priority),
            reason=getattr(evidence, "reason", None),
            batch_index=int(getattr(evidence, "batch_index", 0)),
            row_offset=int(getattr(evidence, "row_offset", 0)),
            checkpointed=bool(getattr(evidence, "checkpointed", False)),
            preemptions=ticket.preemptions,
        )
        return True

    def _release_lease(self, lease: Any, group: List[RunTicket]) -> None:
        """Return the group's slice to the pool — via ``revoke`` (the
        accounted preemption variant) when any member carries
        checkpoint evidence, plain ``release`` otherwise."""
        preempted = [
            t
            for t in group
            if preempt_checkpoint_evidence(t) is not None
        ]
        if preempted and hasattr(self.placer, "revoke"):
            self.placer.revoke(
                lease,
                run_ids=[t.handle.run_id for t in preempted],
            )
        else:
            self.placer.release(lease)

    def _place_group(self, group: List[RunTicket]) -> Any:
        """Lease ONE device slice for the whole group (coalesced
        members run in one superset scan over the same dataset, so the
        largest member's footprint sizes the slice). Blocks until the
        pool can serve it; every member's budget keeps burning and any
        member's cancel stays live while waiting."""
        estimated = max(
            (ticket.estimated_bytes or 0) for ticket in group
        )
        lead = group[0]
        interactive = any(
            t.handle.priority == Priority.INTERACTIVE for t in group
        )
        if (
            self.preemption is not None
            and interactive
            and not self._pool_has_room()
        ):
            # the pool is exhausted at the moment an interactive group
            # needs a slice: preempt NOW so the blocking place() below
            # is bounded by one batch boundary, not a batch residency
            self.preemption.preempt_for(lead.handle.run_id)
        if self.preemption is not None and interactive:
            with self._state_lock:
                self._capacity_waits += 1
            try:
                return self._place_group_inner(group, estimated, lead)
            finally:
                with self._state_lock:
                    self._capacity_waits -= 1
        return self._place_group_inner(group, estimated, lead)

    def _place_group_inner(
        self, group: List[RunTicket], estimated: int, lead: RunTicket
    ) -> Any:
        lease = self.placer.place(
            estimated_bytes=estimated,
            hint=(lead.dataset_key, lead.coalesce_surface),
            run_ids=[t.handle.run_id for t in group],
            budgets=[t.budget for t in group],
            cancels=[t.handle.cancel_token for t in group],
        )
        tm = get_telemetry()
        for ticket in group:
            ticket.lease = lease
            ticket.handle.placement = {
                "ndev": lease.ndev,
                "device_ids": lease.device_ids,
                "lease_wait_s": lease.wait_s,
            }
            if ticket.trace is not None:
                tm.emit_span(
                    "lease_wait",
                    lease.wait_s,
                    trace=ticket.trace,
                    parent_id=ticket.trace.span_id,
                    ndev=lease.ndev,
                )
        return lease

    # -- execution ------------------------------------------------------

    def _run_group(self, group: List[RunTicket]) -> List[Any]:
        if len(group) == 1:
            return [self.execute(group[0])]
        outcomes = list(self.execute_group(group))
        if len(outcomes) != len(group):
            raise RuntimeError(
                f"execute_group returned {len(outcomes)} "
                f"outcomes for {len(group)} tickets"
            )
        return outcomes

    def _run_group_traced(self, group: List[RunTicket]) -> List[Any]:
        """Execute under the HOST ticket's trace: the live ``execute``
        span (and every engine span it nests) lands in the host's tree;
        each other member gets a ``coalesced_scan`` link span in its OWN
        trace pointing at the host's execute span — trace_report follows
        the link to attribute the shared superset scan per member."""
        tm = get_telemetry()
        ctx = group[0].trace
        if ctx is None:
            return self._run_group(group)
        esp_holder: List[Any] = []
        try:
            with tm.trace_scope(ctx):
                with tm.span(
                    "execute", group_size=len(group)
                ) as esp:
                    esp_holder.append(esp)
                    return self._run_group(group)
        finally:
            if esp_holder:
                esp = esp_holder[0]
                for member in group[1:]:
                    if member.trace is None:
                        continue
                    tm.emit_span(
                        "coalesced_scan",
                        esp.wall_s,
                        trace=member.trace,
                        parent_id=member.trace.span_id,
                        link_trace_id=ctx.trace_id,
                        link_span_id=esp.span_id,
                        group_size=len(group),
                    )

    # -- the worker loop ------------------------------------------------

    def _worker_loop(self, index: int) -> None:
        while not self._stop.is_set():
            # targets are re-read every iteration: resize() retargets
            # and this worker reacts at its next pop (scale-down) or
            # class restriction change (reserve adjustment)
            if index >= self.workers:
                return  # autoscaled away
            max_priority = (
                Priority.INTERACTIVE
                if index < self.interactive_reserve
                else None
            )
            group = self.queue.pop_group(
                max_priority=max_priority,
                should_stop=lambda: (
                    self._stop.is_set() or index >= self.workers
                ),
                policy=self.coalesce,
                defer_batch=(
                    self._defer_batch
                    if self.preemption is not None
                    else None
                ),
            )
            if group is None:
                if self._stop.is_set() or index >= self.workers:
                    return  # stopping, or scaled down mid-wait
                continue
            with self._state_lock:
                self._busy += 1
            try:
                self._serve_group(group)
            finally:
                with self._state_lock:
                    self._busy -= 1

    def _fenced_drop(self, group: List[RunTicket]) -> bool:
        """True when the fence says this replica lost its epoch: log
        the dropped group and let the caller skip every terminal
        transition. The handles stay non-terminal on purpose — in this
        process the runs have no true outcome; the adopter's copies
        do."""
        if self.fence is None or self.fence():
            return False
        from deequ_tpu.telemetry import get_telemetry

        get_telemetry().event(
            "scheduler_group_fenced",
            run_ids=",".join(t.handle.run_id for t in group),
            members=len(group),
        )
        return True

    def _serve_group(self, group: List[RunTicket]) -> None:
        lease = None
        record = None
        if self.placer is not None:
            try:
                lease = self._place_group(group)
            # lint-ok: interrupt-swallow: same contract as the
            # execute path below — a lease the group could not get
            # in time (DeadlineExceeded/RunCancelled) terminates
            # the members through their handles, not the worker
            except BaseException as exc:  # noqa: BLE001
                for ticket in group:
                    self._finish_failed(ticket, exc)
                    self.queue.task_done(ticket)
                return
        if self.preemption is not None:
            record = self.preemption.register(group)
        for ticket in group:
            self._mark_started(ticket, len(group))
        try:
            outcomes: List[Any] = self._run_group_traced(group)
        # lint-ok: interrupt-swallow: the handles are the error
        # channel — _finish(FAILED, error=exc) carries everything
        # (interrupts included) to result(); the worker thread
        # itself must survive any run
        except BaseException as exc:  # noqa: BLE001
            if self._fenced_drop(group):
                pass
            else:
                for ticket in group:
                    if not self._requeue_preempted(ticket, exc):
                        self._finish_failed(ticket, exc)
        else:
            if self._fenced_drop(group):
                pass
            else:
                for ticket, outcome in zip(group, outcomes):
                    if not self._requeue_preempted(ticket, outcome):
                        self._finish_outcome(ticket, outcome)
        finally:
            if record is not None:
                self.preemption.deregister(record)
            if lease is not None:
                self._release_lease(lease, group)
            for ticket in group:
                self.queue.task_done(ticket)
