"""Metrics repository: timestamped + tagged persisted metric series.

Reference: ``src/main/scala/com/amazon/deequ/repository/`` (SURVEY.md
§2.5, §5.5): ``MetricsRepository`` saves/loads ``AnalysisResult`` by
``ResultKey(timestamp, tags)``; the query loader supports time-travel
(``after``/``before``) and tag filtering; results export as records/JSON.
This layer is pure Python (engine-agnostic, SURVEY.md §1) and feeds
anomaly detection.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.runner import AnalyzerContext


@dataclass(frozen=True)
class ResultKey:
    """Identifies one analysis run: epoch-millis timestamp + tags."""

    dataset_date: int
    tags: Tuple[Tuple[str, str], ...] = ()

    @staticmethod
    def of(dataset_date: Optional[int] = None, tags: Optional[Dict[str, str]] = None) -> "ResultKey":
        if dataset_date is None:
            dataset_date = ResultKey.current_milli_time()
        return ResultKey(dataset_date, tuple(sorted((tags or {}).items())))

    @staticmethod
    def current_milli_time() -> int:
        return int(time.time() * 1000)

    @property
    def tags_dict(self) -> Dict[str, str]:
        return dict(self.tags)


@dataclass
class AnalysisResult:
    result_key: ResultKey
    analyzer_context: AnalyzerContext


class MetricsRepository:
    def save(self, result: AnalysisResult) -> None:
        raise NotImplementedError

    def load_by_key(self, key: ResultKey) -> Optional[AnalysisResult]:
        raise NotImplementedError

    def load(self) -> "MetricsRepositoryMultipleResultsLoader":
        raise NotImplementedError


class MetricsRepositoryMultipleResultsLoader:
    """Fluent time-travel query over stored results (reference:
    ``repository.load().after(t).before(t).withTagValues(m).get...``)."""

    def __init__(self, results: Sequence[AnalysisResult]):
        self._results = list(results)
        self._after: Optional[int] = None
        self._before: Optional[int] = None
        self._tag_values: Optional[Dict[str, str]] = None
        self._for_analyzers: Optional[List[Analyzer]] = None

    def after(self, dataset_date: int) -> "MetricsRepositoryMultipleResultsLoader":
        self._after = dataset_date
        return self

    def before(self, dataset_date: int) -> "MetricsRepositoryMultipleResultsLoader":
        self._before = dataset_date
        return self

    def with_tag_values(self, tag_values: Dict[str, str]) -> "MetricsRepositoryMultipleResultsLoader":
        self._tag_values = tag_values
        return self

    def for_analyzers(self, analyzers: Sequence[Analyzer]) -> "MetricsRepositoryMultipleResultsLoader":
        self._for_analyzers = list(analyzers)
        return self

    def get(self) -> List[AnalysisResult]:
        out = []
        for result in self._results:
            key = result.result_key
            if self._after is not None and key.dataset_date < self._after:
                continue
            if self._before is not None and key.dataset_date > self._before:
                continue
            if self._tag_values is not None:
                tags = key.tags_dict
                if any(tags.get(k) != v for k, v in self._tag_values.items()):
                    continue
            context = result.analyzer_context
            if self._for_analyzers is not None:
                context = AnalyzerContext(
                    {
                        a: m
                        for a, m in context.metric_map.items()
                        if a in self._for_analyzers
                    }
                )
            out.append(AnalysisResult(key, context))
        return sorted(out, key=lambda r: r.result_key.dataset_date)

    def get_success_metrics_as_records(self) -> List[Dict]:
        records = []
        for result in self.get():
            for rec in result.analyzer_context.success_metrics_as_records():
                rec = dict(rec)
                rec["dataset_date"] = result.result_key.dataset_date
                rec.update(result.result_key.tags_dict)
                records.append(rec)
        return records

    def get_success_metrics_as_json(self) -> str:
        return json.dumps(self.get_success_metrics_as_records(), indent=2)

    def get_success_metrics_as_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.get_success_metrics_as_records())


class InMemoryMetricsRepository(MetricsRepository):
    """Reference: repository/memory/InMemoryMetricsRepository.scala —
    which uses a ConcurrentHashMap (SURVEY.md §5.2); a lock gives the
    same concurrent-writer safety here."""

    def __init__(self) -> None:
        self._store: Dict[ResultKey, AnalysisResult] = {}
        self._lock = threading.Lock()

    def save(self, result: AnalysisResult) -> None:
        _bump("repository.saves")
        with self._lock:
            self._store[result.result_key] = result

    def load_by_key(self, key: ResultKey) -> Optional[AnalysisResult]:
        _bump("repository.loads")
        with self._lock:
            return self._store.get(key)

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        _bump("repository.loads")
        with self._lock:
            return MetricsRepositoryMultipleResultsLoader(
                list(self._store.values())
            )


def _bump(counter: str) -> None:
    from deequ_tpu.telemetry import get_telemetry

    get_telemetry().counter(counter).inc()
