"""Anomaly-detection strategy tests: every strategy on synthetic series
with hand-computed expected anomalies (reference test model: one test
per strategy under anomalydetection/, incl. HoltWintersTest —
SURVEY.md §4)."""

import numpy as np
import pytest

from deequ_tpu.anomalydetection.base import (
    AnomalyDetector,
    DataPoint,
)
from deequ_tpu.anomalydetection.seasonal import (
    HoltWinters,
    MetricInterval,
    SeriesSeasonality,
)
from deequ_tpu.anomalydetection.strategies import (
    AbsoluteChangeStrategy,
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RelativeRateOfChangeStrategy,
    SimpleThresholdStrategy,
)


def indices(found):
    return [i for i, _ in found]


class TestSimpleThreshold:
    def test_bounds(self):
        s = SimpleThresholdStrategy(lower_bound=-1.0, upper_bound=1.0)
        found = s.detect([-2.0, -1.0, 0.0, 1.0, 2.0])
        assert indices(found) == [0, 4]
        assert found[0][1].value == -2.0

    def test_search_interval(self):
        s = SimpleThresholdStrategy(upper_bound=1.0)
        found = s.detect([5.0, 5.0, 0.0, 5.0], search_interval=(2, 4))
        assert indices(found) == [3]

    def test_invalid_bounds(self):
        with pytest.raises(ValueError):
            SimpleThresholdStrategy(lower_bound=2.0, upper_bound=1.0)


class TestAbsoluteChange:
    def test_first_order(self):
        s = AbsoluteChangeStrategy(max_rate_decrease=-2.0, max_rate_increase=2.0)
        # diffs: 1, 1, 5, 1 -> index 3 jumps by 5
        found = s.detect([1.0, 2.0, 3.0, 8.0, 9.0])
        assert indices(found) == [3]

    def test_second_order(self):
        s = AbsoluteChangeStrategy(
            max_rate_decrease=-1.0, max_rate_increase=1.0, order=2
        )
        # second differences of [1,2,3,10,4]: [0, 6, -13]
        found = s.detect([1.0, 2.0, 3.0, 10.0, 4.0])
        assert indices(found) == [3, 4]

    def test_short_series(self):
        s = AbsoluteChangeStrategy(order=3)
        assert s.detect([1.0, 2.0]) == []

    def test_invalid_params(self):
        with pytest.raises(ValueError):
            AbsoluteChangeStrategy(max_rate_decrease=1.0, max_rate_increase=0.0)
        with pytest.raises(ValueError):
            AbsoluteChangeStrategy(order=0)


class TestRelativeRateOfChange:
    def test_ratio_band(self):
        s = RelativeRateOfChangeStrategy(
            max_rate_decrease=0.5, max_rate_increase=2.0
        )
        # ratios: 2.0 (ok), 3.0 (high), 1/6 (low)
        found = s.detect([1.0, 2.0, 6.0, 1.0])
        assert indices(found) == [2, 3]


class TestOnlineNormal:
    def test_spike_detected(self):
        rng = np.random.default_rng(0)
        values = list(rng.normal(10.0, 1.0, 50))
        values[40] = 100.0
        s = OnlineNormalStrategy()
        found = s.detect(values)
        assert 40 in indices(found)

    def test_ignore_anomalies_keeps_estimate_clean(self):
        """With ignore_anomalies, a detected spike does not inflate the
        running stddev, so a later smaller spike is still caught."""
        rng = np.random.default_rng(1)
        values = list(rng.normal(0.0, 1.0, 60))
        values[30] = 50.0
        values[45] = 10.0  # ~10 sigma, caught only if 50.0 was excluded
        caught = indices(OnlineNormalStrategy(ignore_anomalies=True).detect(values))
        assert 30 in caught and 45 in caught


class TestBatchNormal:
    def test_trains_outside_interval(self):
        rng = np.random.default_rng(2)
        values = list(rng.normal(5.0, 0.5, 30)) + [5.1, 20.0, 4.9]
        s = BatchNormalStrategy()
        found = s.detect(values, search_interval=(30, 33))
        assert indices(found) == [31]

    def test_needs_training_points(self):
        with pytest.raises(ValueError):
            BatchNormalStrategy().detect([1.0, 2.0], search_interval=(0, 2))


class TestHoltWinters:
    @staticmethod
    def weekly_series(weeks, spike_at=None):
        """Additive weekly pattern + mild trend."""
        pattern = np.array([10.0, 12.0, 14.0, 13.0, 11.0, 5.0, 4.0])
        series = np.concatenate([pattern] * weeks)
        series = series + 0.05 * np.arange(len(series))
        if spike_at is not None:
            series[spike_at] += 15.0
        return list(series)

    def test_forecast_accurate_on_clean_series(self):
        values = self.weekly_series(5)
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        found = s.detect(values, search_interval=(28, 35))
        assert found == []

    def test_spike_in_forecast_window(self):
        values = self.weekly_series(5, spike_at=30)
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        found = s.detect(values, search_interval=(28, 35))
        assert indices(found) == [30]

    def test_requires_two_periods_of_history(self):
        s = HoltWinters(MetricInterval.DAILY, SeriesSeasonality.WEEKLY)
        with pytest.raises(ValueError):
            s.detect(self.weekly_series(2), search_interval=(10, 14))

    def test_monthly_yearly_period(self):
        pattern = np.arange(12, dtype=float) * 2.0 + 3.0
        values = list(np.concatenate([pattern] * 3))
        values[30] += 40.0
        s = HoltWinters(MetricInterval.MONTHLY, SeriesSeasonality.YEARLY)
        found = s.detect(values, search_interval=(24, 36))
        assert indices(found) == [30]


class TestAnomalyDetector:
    def test_new_point_anomalous(self):
        history = [DataPoint(t, 1.0) for t in range(10)]
        detector = AnomalyDetector(SimpleThresholdStrategy(upper_bound=2.0))
        assert detector.is_new_point_anomalous(
            history, DataPoint(10, 5.0)
        ).is_anomalous
        assert not detector.is_new_point_anomalous(
            history, DataPoint(10, 1.5)
        ).is_anomalous

    def test_history_sorted_and_nulls_dropped(self):
        history = [
            DataPoint(3, 3.0),
            DataPoint(1, 1.0),
            DataPoint(2, None),
            DataPoint(0, 0.0),
        ]
        detector = AnomalyDetector(
            AbsoluteChangeStrategy(max_rate_decrease=-1.5, max_rate_increase=1.5)
        )
        result = detector.is_new_point_anomalous(history, DataPoint(4, 13.0))
        assert result.is_anomalous
        # anomaly reported against the new point's timestamp
        assert result.anomalies[0][0] == 4


class TestHoltWintersMultiplicative:
    def test_scaling_seasonal_series(self):
        """Seasonal swing proportional to level: the multiplicative model
        fits where the additive one underestimates the growing peaks."""
        from deequ_tpu.anomalydetection.seasonal import SeasonalityModel

        pattern = np.array([1.0, 1.5, 2.0, 1.5, 1.0, 0.5, 0.5])
        weeks = 6
        values = list(np.concatenate([pattern] * weeks) * 100.0
                      * (1.0 + 0.02 * np.arange(weeks * 7)))
        s = HoltWinters(
            MetricInterval.DAILY,
            SeriesSeasonality.WEEKLY,
            model=SeasonalityModel.MULTIPLICATIVE,
        )
        clean = s.detect(values, search_interval=(35, 42))
        assert clean == []
        spiked = list(values)
        spiked[38] *= 2.0
        found = s.detect(spiked, search_interval=(35, 42))
        assert indices(found) == [38]

    def test_requires_positive_series(self):
        from deequ_tpu.anomalydetection.seasonal import SeasonalityModel

        s = HoltWinters(model=SeasonalityModel.MULTIPLICATIVE)
        with pytest.raises(ValueError):
            s.detect([0.0] * 30, search_interval=(14, 20))

    def test_zero_inside_search_interval_is_an_anomaly(self):
        """A collapse to zero in the forecast window must be REPORTED,
        not rejected by the positivity guard (which applies to the
        training slice only)."""
        from deequ_tpu.anomalydetection.seasonal import SeasonalityModel

        pattern = np.array([1.0, 1.5, 2.0, 1.5, 1.0, 0.5, 0.5])
        values = list(np.concatenate([pattern] * 6) * 100.0)
        values[38] = 0.0
        s = HoltWinters(model=SeasonalityModel.MULTIPLICATIVE)
        found = s.detect(values, search_interval=(35, 42))
        assert 38 in indices(found)
