"""Fence-discipline analyzer: no persist call without an epoch fence.

The fencing invariant of fleet failover (docs/SERVICE.md "Fleet
failover"): once a replica's lease epoch has been superseded by an
adopter, every journal/repository persist it attempts would corrupt
state the adopter now owns — a zombie terminal record marks an adopted
run finished in a journal nobody replays, a zombie repository save
double-appends a result the adopter also persists. The runtime guard
is ``epoch_fence_check`` (service/fleet.py), which returns False (and
counts ``service.fleet.fenced_writes``) for a superseded epoch.

The rule is structural, the house style of ``preempt-discipline``:
inside ``deequ_tpu/service/``, every call to a journal persist method
(``record_submitted`` / ``record_started`` / ``record_checkpoint`` /
``record_preempted`` / ``record_resumed`` / ``record_terminal`` /
``record_adoption_intent`` / ``record_adoption_done``) or a
repository ``save`` must be LEXICALLY PRECEDED, within the same
enclosing function, by a call to ``epoch_fence_check`` — the
fence -> persist ordering made checkable. Flow-insensitive on purpose:
the fence is sticky (a superseded epoch is never reclaimed), so any
earlier check in the function covers every later persist. Method
DEFINITIONS are exempt by construction (``super().save(...)`` has a
computed callee and record_* bodies call ``self.append``); sites with
a structural fence of their own (e.g. a write published by the lease
CAS itself) carry a ``# lint-ok: fence-discipline: <reason>`` waiver.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

SCOPE_PREFIX = "deequ_tpu/service/"

GUARDED_ATTRS = frozenset(
    {
        "record_submitted",
        "record_started",
        "record_checkpoint",
        "record_preempted",
        "record_resumed",
        "record_terminal",
        "record_adoption_intent",
        "record_adoption_done",
        "save",
    }
)
EVIDENCE_NAME = "epoch_fence_check"


def _call_name(node: ast.Call) -> Optional[str]:
    """The last path segment of the called name ('save' for
    ``repository.save(...)``), or None for computed callees."""
    name = dotted_name(node.func)
    if name is None:
        return None
    return name.split(".")[-1]


def _function_sites(
    tree: ast.AST,
) -> Iterable[Tuple[Optional[ast.AST], List[ast.Call]]]:
    """(enclosing function, calls directly inside it) pairs; calls in
    nested functions belong to the NESTED function (each scope must
    establish its own fence), module-level calls to None."""
    functions = [
        node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    owner: dict[int, ast.AST] = {}
    for fn in functions:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                # innermost function wins: walk visits outer functions
                # first, so a later (nested) owner overwrites
                owner[id(node)] = fn
    by_fn: dict[int, List[ast.Call]] = {}
    module_level: List[ast.Call] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = owner.get(id(node))
        if fn is None:
            module_level.append(node)
        else:
            by_fn.setdefault(id(fn), []).append(node)
    for fn in functions:
        yield fn, by_fn.get(id(fn), [])
    if module_level:
        yield None, module_level


class FenceDisciplineAnalyzer(Analyzer):
    name = "fence"
    rules = ("fence-discipline",)
    description = (
        "journal/repository persist call sites in deequ_tpu/service/ "
        "not preceded by an epoch fence check"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if not sf.rel.startswith(SCOPE_PREFIX) or sf.tree is None:
                continue
            if sf.rel == SCOPE_PREFIX + "journal.py":
                # the journal module DEFINES the persist vocabulary
                # (record_* bodies delegate to self.append); it holds
                # no fleet state and cannot fence itself
                continue
            for fn, calls in _function_sites(sf.tree):
                evidence_lines = [
                    c.lineno
                    for c in calls
                    if _call_name(c) == EVIDENCE_NAME
                ]
                first_evidence = (
                    min(evidence_lines) if evidence_lines else None
                )
                for call in calls:
                    attr = _call_name(call)
                    if attr not in GUARDED_ATTRS:
                        continue
                    if not isinstance(call.func, ast.Attribute):
                        continue  # a local helper, not a persist target
                    if (
                        first_evidence is not None
                        and first_evidence < call.lineno
                    ):
                        continue
                    where = (
                        f"function {getattr(fn, 'name', '?')!r}"
                        if fn is not None
                        else "module level"
                    )
                    yield Finding(
                        rule="fence-discipline",
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            f".{attr}() at {where} without a preceding "
                            f"{EVIDENCE_NAME}() call — a persist is "
                            "only licensed while this replica still "
                            "owns its lease epoch (docs/SERVICE.md "
                            '"Fleet failover")'
                        ),
                        symbol=attr,
                    )


register(FenceDisciplineAnalyzer())
