"""Holt-Winters seasonal anomaly detection.

Reference: ``anomalydetection/seasonal/HoltWinters.scala`` (SURVEY.md
§2.5): additive triple exponential smoothing, trained on history, then
forecasting the search interval; a point is anomalous when the forecast
error exceeds a bound derived from the training residuals. The reference
tunes (alpha, beta, gamma) with a derivative-free optimizer (BOBYQA);
here a coarse-to-fine grid search over the smoothing parameters plays
that role — same model, same anomaly rule.
"""

from __future__ import annotations

import enum
import itertools
import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.anomalydetection.base import Anomaly, AnomalyDetectionStrategy
from deequ_tpu.anomalydetection.strategies import _resolve_interval


class MetricInterval(enum.Enum):
    DAILY = "Daily"
    MONTHLY = "Monthly"


class SeriesSeasonality(enum.Enum):
    WEEKLY = "Weekly"
    YEARLY = "Yearly"


def _period(interval: MetricInterval, seasonality: SeriesSeasonality) -> int:
    if (interval, seasonality) == (MetricInterval.DAILY, SeriesSeasonality.WEEKLY):
        return 7
    if (interval, seasonality) == (MetricInterval.MONTHLY, SeriesSeasonality.YEARLY):
        return 12
    if (interval, seasonality) == (MetricInterval.DAILY, SeriesSeasonality.YEARLY):
        return 365
    raise ValueError(
        f"unsupported interval/seasonality combination: "
        f"{interval}/{seasonality}"
    )


class SeasonalityModel(enum.Enum):
    ADDITIVE = "Additive"
    MULTIPLICATIVE = "Multiplicative"


def _holt_winters_additive(
    series: np.ndarray, period: int, alpha: float, beta: float, gamma: float
) -> Tuple[np.ndarray, float, float, np.ndarray]:
    """One smoothing pass; returns (fitted one-step forecasts, final
    level, final trend, final season array)."""
    n = len(series)
    seasons = series[:period] - series[:period].mean()
    level = float(series[:period].mean())
    trend = float(
        (series[period : 2 * period].mean() - series[:period].mean()) / period
    ) if n >= 2 * period else 0.0
    season = seasons.astype(float).copy()
    fitted = np.empty(n)
    for i in range(n):
        s = season[i % period]
        fitted[i] = level + trend + s
        value = series[i]
        new_level = alpha * (value - s) + (1 - alpha) * (level + trend)
        trend = beta * (new_level - level) + (1 - beta) * trend
        season[i % period] = gamma * (value - new_level) + (1 - gamma) * s
        level = new_level
    return fitted, level, trend, season


def _holt_winters_multiplicative(
    series: np.ndarray, period: int, alpha: float, beta: float, gamma: float
) -> Tuple[np.ndarray, float, float, np.ndarray]:
    """Multiplicative-seasonality variant (reference:
    seasonal/HoltWinters MultiplicativeSeasonality): season is a FACTOR
    on the level, appropriate when seasonal swing scales with the
    series magnitude. Requires a positive series."""
    n = len(series)
    base = float(series[:period].mean())
    if base == 0:
        base = 1e-12
    season = (series[:period] / base).astype(float).copy()
    level = base
    trend = float(
        (series[period : 2 * period].mean() - series[:period].mean()) / period
    ) if n >= 2 * period else 0.0
    fitted = np.empty(n)
    for i in range(n):
        s = season[i % period]
        fitted[i] = (level + trend) * s
        value = series[i]
        safe_s = s if s != 0 else 1e-12
        new_level = alpha * (value / safe_s) + (1 - alpha) * (level + trend)
        trend = beta * (new_level - level) + (1 - beta) * trend
        safe_level = new_level if new_level != 0 else 1e-12
        season[i % period] = gamma * (value / safe_level) + (1 - gamma) * s
        level = new_level
    return fitted, level, trend, season


def _forecast(
    level: float,
    trend: float,
    season: np.ndarray,
    start: int,
    steps: int,
    period: int,
    multiplicative: bool = False,
) -> np.ndarray:
    if multiplicative:
        return np.array(
            [
                (level + (h + 1) * trend) * season[(start + h) % period]
                for h in range(steps)
            ]
        )
    return np.array(
        [
            level + (h + 1) * trend + season[(start + h) % period]
            for h in range(steps)
        ]
    )


@dataclass
class HoltWinters(AnomalyDetectionStrategy):
    metric_interval: MetricInterval = MetricInterval.DAILY
    seasonality: SeriesSeasonality = SeriesSeasonality.WEEKLY
    model: SeasonalityModel = SeasonalityModel.ADDITIVE

    def _smooth(self, train, period, a, b, g):
        if self.model == SeasonalityModel.MULTIPLICATIVE:
            return _holt_winters_multiplicative(train, period, a, b, g)
        return _holt_winters_additive(train, period, a, b, g)

    def _fit(
        self, train: np.ndarray, period: int
    ) -> Tuple[Tuple[float, float, float], float]:
        """Coarse-to-fine grid search minimizing in-sample MSE."""
        best = (0.3, 0.1, 0.1)
        best_mse = math.inf
        grid = [0.05, 0.2, 0.4, 0.6, 0.8, 0.95]
        for a, b, g in itertools.product(grid, grid, grid):
            fitted, *_ = self._smooth(train, period, a, b, g)
            mse = float(np.mean((fitted - train) ** 2))
            if mse < best_mse:
                best_mse, best = mse, (a, b, g)
        # refine around the winner
        a0, b0, g0 = best
        fine = lambda c: [max(0.01, c - 0.1), c, min(0.99, c + 0.1)]
        for a, b, g in itertools.product(fine(a0), fine(b0), fine(g0)):
            fitted, *_ = self._smooth(train, period, a, b, g)
            mse = float(np.mean((fitted - train) ** 2))
            if mse < best_mse:
                best_mse, best = mse, (a, b, g)
        return best, best_mse

    def detect(self, values, search_interval=None):
        values = np.asarray(values, dtype=float)
        n = len(values)
        period = _period(self.metric_interval, self.seasonality)
        lo, hi = _resolve_interval(n, search_interval)
        if lo < 2 * period:
            raise ValueError(
                f"Holt-Winters requires at least two full periods "
                f"({2 * period} points) of history before the search "
                f"interval, got {lo}"
            )
        if (
            self.model == SeasonalityModel.MULTIPLICATIVE
            and np.any(values[:lo] <= 0)
        ):
            # only the TRAINING slice is divided by; a zero inside the
            # search interval is a candidate anomaly, not a model error
            raise ValueError(
                "multiplicative Holt-Winters requires a positive "
                "training series"
            )
        train = values[:lo]
        (a, b, g), _ = self._fit(train, period)
        fitted, level, trend, season = self._smooth(train, period, a, b, g)
        residual_sd = float(np.std(train - fitted))
        forecasts = _forecast(
            level, trend, season, lo, hi - lo, period,
            multiplicative=self.model == SeasonalityModel.MULTIPLICATIVE,
        )
        bound = 1.96 * residual_sd
        out: List[Tuple[int, Anomaly]] = []
        for offset, i in enumerate(range(lo, hi)):
            error = values[i] - forecasts[offset]
            if abs(error) > bound:
                out.append(
                    (
                        i,
                        Anomaly(
                            float(values[i]),
                            1.0,
                            f"[HoltWinters]: forecast {forecasts[offset]}, "
                            f"observed {values[i]}, error {error} beyond "
                            f"±{bound}",
                        ),
                    )
                )
        return out
