"""Minimal crash reproducer for the streamed-child crash family.

ROADMAP item 1a: streamed configs occasionally die in spawn children
with SIGSEGV/SIGABRT at >= ~800k rows.  This tool drives ONE streamed
parquet config at a time under the isolation harness
(:class:`deequ_tpu.engine.subproc.IsolatedRunner`, single attempt, no
breaker) and bisects the three suspect dimensions:

- ``batch_size``     — halved while the crash still reproduces
- ``xla_cache``      — persistent XLA compilation cache on/off (the
                       PR 12 ops note flagged a poisoned cache entry
                       as a suspect: if turning the cache off makes
                       the crash vanish, the cache is implicated)
- ``ingest_workers`` — parallel ingest vs the serial bit-identical
                       path (``ingest_workers=1``)
- ``rows``           — halved while the crash still reproduces, to
                       find the smallest dataset that still dies

The output is a single JSON verdict naming the narrowest reproducing
config, whether the persistent XLA cache is implicated, and the full
trial log::

    python -m tools.crash_repro --rows 1000000 --out verdict.json

The bisection core (:func:`bisect_crash`) is pure — it takes any
``probe(config) -> {"crashed": bool, ...}`` callable — so the search
logic is unit-testable without ever spawning a child.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

MIN_BATCH = 1 << 12
MIN_ROWS = 50_000

BASE_CONFIG: Dict[str, Any] = {
    # ROADMAP pins the family at >= ~800k rows; start just above
    "rows": 1_000_000,
    # engine default batch (None) is where the crashes were seen; the
    # bisect needs a concrete number to halve, so start at the
    # streaming bench's 512k
    "batch_size": 1 << 19,
    "ingest_workers": 0,  # 0 = auto (parallel ingest)
    "xla_cache": True,  # persistent compilation cache enabled
}


# -- pure bisection core ------------------------------------------------


def bisect_crash(
    probe: Callable[[Dict[str, Any]], Dict[str, Any]],
    base: Optional[Dict[str, Any]] = None,
    *,
    min_batch: int = MIN_BATCH,
    min_rows: int = MIN_ROWS,
) -> Dict[str, Any]:
    """Shrink ``base`` one dimension at a time, keeping every step
    that still reproduces.  Returns the verdict dict.

    ``probe`` runs one config and reports ``{"crashed": bool, ...}``;
    extra keys (signal name, detail) are carried into the trial log.
    """
    base = dict(BASE_CONFIG if base is None else base)
    trials: List[Dict[str, Any]] = []

    def attempt(cfg: Dict[str, Any], label: str) -> bool:
        outcome = probe(dict(cfg))
        trials.append(
            {"label": label, "config": dict(cfg), "outcome": outcome}
        )
        return bool(outcome.get("crashed"))

    verdict: Dict[str, Any] = {
        "reproduced": False,
        "baseline": dict(base),
        "narrowest": None,
        "xla_cache_implicated": False,
        "trials": trials,
    }
    if not attempt(base, "baseline"):
        return verdict
    verdict["reproduced"] = True
    narrowest = dict(base)

    # 1. persistent XLA cache: flip it off first — if the crash
    #    vanishes without it, the poisoned-cache suspicion is confirmed
    #    and every later trial keeps the cache ON to stay in the
    #    reproducing family
    if narrowest.get("xla_cache"):
        candidate = dict(narrowest, xla_cache=False)
        if attempt(candidate, "xla_cache_off"):
            narrowest = candidate  # crashes either way: cache innocent
        else:
            verdict["xla_cache_implicated"] = True

    # 2. batch size: halve while the crash survives
    while narrowest["batch_size"] // 2 >= min_batch:
        candidate = dict(narrowest, batch_size=narrowest["batch_size"] // 2)
        if not attempt(candidate, "halve_batch"):
            break
        narrowest = candidate

    # 3. ingest workers: the serial path is the narrowest claim — if
    #    it still crashes, parallel ingest is off the hook
    if narrowest["ingest_workers"] != 1:
        candidate = dict(narrowest, ingest_workers=1)
        if attempt(candidate, "serial_ingest"):
            narrowest = candidate

    # 4. rows: halve while the crash survives
    while narrowest["rows"] // 2 >= min_rows:
        candidate = dict(narrowest, rows=narrowest["rows"] // 2)
        if not attempt(candidate, "halve_rows"):
            break
        narrowest = candidate

    verdict["narrowest"] = narrowest
    return verdict


# -- the real probe: one streamed config under the isolation harness ----


def _child_scan(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Runs IN THE SPAWN CHILD: one streamed profile pass over the
    sharded parquet table with the bisected knobs applied."""
    from deequ_tpu import config
    from deequ_tpu.data import Dataset
    from deequ_tpu.profiles.profiler import ColumnProfiler

    overrides: Dict[str, Any] = {
        # device cache off => every byte re-streams (the crash family
        # is exclusive to streamed configs)
        "device_cache_bytes": 0,
        "batch_size": int(payload["batch_size"]),
        "ingest_workers": int(payload["ingest_workers"]),
    }
    if not payload["xla_cache"]:
        overrides["compilation_cache_dir"] = ""  # disables the cache
    with config.configure(**overrides):
        profiles = ColumnProfiler.profile(
            Dataset.from_parquet(payload["data_dir"])
        )
    return {"columns": len(profiles.profiles)}


def _write_shards(data_dir: str, rows: int, shards: int = 4) -> None:
    """Synthetic multi-file parquet table shaped like the failing
    workloads: int64 keys, f64 measures, dictionary strings."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(1729)
    table = pa.table(
        {
            "key": pa.array(rng.integers(0, 1 << 31, size=rows)),
            "qty": pa.array(rng.integers(0, 100, size=rows)),
            "price": pa.array(rng.random(rows) * 500.0),
            "status": pa.array(
                np.array(["ok", "hold", "void"])[
                    rng.integers(0, 3, size=rows)
                ]
            ),
        }
    )
    shard_rows = rows // shards
    for i in range(shards):
        length = None if i == shards - 1 else shard_rows
        pq.write_table(
            table.slice(i * shard_rows, length),
            os.path.join(data_dir, f"part{i}.parquet"),
        )


class IsolatedProbe:
    """Probe one config in a spawn child; a child death (any signal)
    counts as "reproduced".  Single attempt — no relaunch, no breaker:
    a reproducer must observe the first crash, not recover from it."""

    def __init__(self, workdir: str, *, timeout_s: float = 600.0):
        self.workdir = workdir
        self.timeout_s = timeout_s
        self._data_dirs: Dict[int, str] = {}

    def _data_dir(self, rows: int) -> str:
        cached = self._data_dirs.get(rows)
        if cached is not None:
            return cached
        data_dir = os.path.join(self.workdir, f"rows{rows}")
        os.makedirs(data_dir, exist_ok=True)
        _write_shards(data_dir, rows)
        self._data_dirs[rows] = data_dir
        return data_dir

    def __call__(self, cfg: Dict[str, Any]) -> Dict[str, Any]:
        from deequ_tpu.engine.subproc import CrashLoopError, IsolatedRunner

        payload = {
            "data_dir": self._data_dir(int(cfg["rows"])),
            "batch_size": int(cfg["batch_size"]),
            "ingest_workers": int(cfg["ingest_workers"]),
            "xla_cache": bool(cfg["xla_cache"]),
        }
        runner = IsolatedRunner(
            key="crash-repro",
            max_relaunches=1,  # first crash ends the attempt
            use_breaker=False,
            timeout_s=self.timeout_s,
        )
        try:
            result = runner.run(_child_scan, payload)
        except CrashLoopError as crash:
            return {
                "crashed": True,
                "signal": crash.last_signal,
                "exitcode": crash.last_exitcode,
                "detail": str(crash),
            }
        except Exception as exc:  # in-band child error: NOT a crash
            return {"crashed": False, "error": repr(exc)}
        return {"crashed": False, "result": result}


# -- CLI ----------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="crash_repro",
        description=(
            "bisect the streamed-child crash family to its narrowest "
            "reproducing config (ROADMAP item 1a)"
        ),
    )
    parser.add_argument(
        "--rows",
        type=int,
        default=BASE_CONFIG["rows"],
        help="baseline row count (default: %(default)s)",
    )
    parser.add_argument(
        "--batch-size",
        type=int,
        default=BASE_CONFIG["batch_size"],
        help="baseline batch size (default: %(default)s)",
    )
    parser.add_argument(
        "--timeout-s",
        type=float,
        default=600.0,
        help="per-trial child deadline (default: %(default)s)",
    )
    parser.add_argument(
        "--out",
        default="",
        help="write the JSON verdict here as well as stdout",
    )
    args = parser.parse_args(argv)

    base = dict(
        BASE_CONFIG, rows=int(args.rows), batch_size=int(args.batch_size)
    )
    workdir = tempfile.mkdtemp(prefix="deequ_tpu_crash_repro_")
    try:
        probe = IsolatedProbe(workdir, timeout_s=args.timeout_s)
        verdict = bisect_crash(probe, base)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    text = json.dumps(verdict, indent=2, sort_keys=True, default=repr)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
    return 0 if verdict["reproduced"] else 1


if __name__ == "__main__":
    sys.exit(main())
