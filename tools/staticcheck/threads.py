"""Thread-discipline analyzer: every thread and queue in the product
tree must be accounted for.

The r10 ingest pool multiplied the number of threads the engine may
run at once, and the teardown contract ("a cancelled scan leaks no
thread", asserted via ``active_prefetch_workers() == []`` in tier-1)
only holds if every ``threading.Thread`` the product constructs is
visible to the leak probe. One rule, three checks:

``thread-discipline``

1. **Sanctioned modules** — ``threading.Thread`` and ``queue.Queue``
   constructions in ``deequ_tpu/`` may only appear in the modules that
   own a documented thread lifecycle (the ingest pool, the legacy
   prefetcher, the watchdog, and the service layer). A thread spawned
   from an analyzer or a codec has no owner to join it.
2. **Leak-probe registration** — each ``Thread`` construction must be
   passed to :func:`deequ_tpu.engine.ingest.register_ingest_thread`
   (directly, or via the name/attribute it was assigned to), so
   ``active_ingest_threads()`` sees it; threads with their own
   joined-on-stop lifecycle (watchdog, service workers) carry a
   reasoned ``# lint-ok: thread-discipline:`` waiver instead.
3. **Bounded queues** — ``queue.Queue()`` must be constructed with a
   ``maxsize > 0``. An unbounded queue between a fast producer and a
   stalled consumer buffers the whole dataset on the host;
   ``SimpleQueue`` is unbounded by construction and always flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

#: modules with a documented thread lifecycle (spawn + join/probe)
SANCTIONED = frozenset(
    {
        "deequ_tpu/engine/deadline.py",
        "deequ_tpu/engine/ingest.py",
        "deequ_tpu/engine/scan.py",
        "deequ_tpu/service/service.py",
        "deequ_tpu/service/scheduler.py",
        # placement's DevicePool waits on a Condition at the injected
        # clock's cadence; any thread/queue it grows must stay bounded
        # and registered like the rest of the service layer
        "deequ_tpu/service/placement.py",
    }
)

#: functions that make a thread visible to the leak probe
REGISTRARS = frozenset({"register_ingest_thread"})

#: queue classes that take a maxsize; SimpleQueue never does
BOUNDED_QUEUE_TAILS = frozenset({"Queue", "LifoQueue", "PriorityQueue"})


def _call_tail(node: ast.Call) -> str:
    return (dotted_name(node.func) or "").split(".")[-1]


def _thread_calls(tree: ast.AST, names: Set[str]) -> List[ast.Call]:
    """Calls constructing ``threading.Thread`` (or a bare ``Thread``
    imported from threading — ``names`` is the from-import set)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if callee == "threading.Thread" or (
            callee == "Thread" and "Thread" in names
        ):
            out.append(node)
    return out


def _from_imports(tree: ast.AST, module: str) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            names.update(alias.asname or alias.name for alias in node.names)
    return names


def _queue_maxsize(node: ast.Call) -> Optional[ast.expr]:
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            return kw.value
    return None


class ThreadDisciplineAnalyzer(Analyzer):
    name = "threads"
    rules = ("thread-discipline",)
    description = (
        "threads/queues only in sanctioned modules, registered with "
        "the ingest leak probe (or waived), queues bounded"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if sf.tree is None or not sf.rel.startswith("deequ_tpu/"):
                continue
            yield from self._analyze_file(sf)

    def _analyze_file(self, sf: SourceFile) -> Iterable[Finding]:
        threading_names = _from_imports(sf.tree, "threading")
        queue_names = _from_imports(sf.tree, "queue")
        thread_calls = _thread_calls(sf.tree, threading_names)

        # registration environment: Thread calls that are arguments of
        # a registrar call, and dotted targets later passed to one
        wrapped: Set[int] = set()
        registered_names: Set[str] = set()
        #: dotted target a Thread call is assigned to, keyed by id()
        assigned_to: Dict[int, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _call_tail(node) in REGISTRARS:
                for arg in node.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Call):
                            wrapped.add(id(sub))
                        name = dotted_name(sub)
                        if name:
                            registered_names.add(name)
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = dotted_name(node.targets[0])
                if target is None:
                    continue
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        assigned_to.setdefault(id(sub), target)

        for call in thread_calls:
            if sf.rel not in SANCTIONED:
                yield Finding(
                    rule="thread-discipline",
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        "Thread constructed outside the sanctioned "
                        "threaded modules — no owner joins it on scan "
                        "teardown; move it into engine/ingest.py, "
                        "engine/scan.py, engine/deadline.py or the "
                        "service layer, or waive with a reason"
                    ),
                    symbol="Thread",
                )
                continue
            target = assigned_to.get(id(call))
            registered = id(call) in wrapped or (
                target is not None and target in registered_names
            )
            if not registered:
                yield Finding(
                    rule="thread-discipline",
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        "Thread construction not registered with the "
                        "ingest leak probe (register_ingest_thread) — "
                        "a leaked thread here is invisible to "
                        "active_prefetch_workers(); register it or "
                        "waive with the lifecycle that joins it"
                    ),
                    symbol="Thread",
                )

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func) or ""
            parts = callee.split(".")
            tail = parts[-1]
            is_queue_mod = parts[0] == "queue" and len(parts) == 2
            is_imported = len(parts) == 1 and tail in queue_names
            if not (is_queue_mod or is_imported):
                continue
            if tail == "SimpleQueue":
                yield Finding(
                    rule="thread-discipline",
                    path=sf.rel,
                    line=node.lineno,
                    message=(
                        "SimpleQueue is unbounded by construction; use "
                        "queue.Queue(maxsize=<bound>) so a stalled "
                        "consumer applies backpressure"
                    ),
                    symbol="SimpleQueue",
                )
                continue
            if tail not in BOUNDED_QUEUE_TAILS:
                continue
            if sf.rel not in SANCTIONED:
                yield Finding(
                    rule="thread-discipline",
                    path=sf.rel,
                    line=node.lineno,
                    message=(
                        "queue constructed outside the sanctioned "
                        "threaded modules; move it next to the thread "
                        "lifecycle that drains it, or waive with a "
                        "reason"
                    ),
                    symbol=tail,
                )
                continue
            maxsize = _queue_maxsize(node)
            unbounded = maxsize is None or (
                isinstance(maxsize, ast.Constant)
                and isinstance(maxsize.value, int)
                and maxsize.value <= 0
            )
            if unbounded:
                yield Finding(
                    rule="thread-discipline",
                    path=sf.rel,
                    line=node.lineno,
                    message=(
                        "unbounded queue: construct with maxsize > 0 "
                        "so the producer blocks instead of buffering "
                        "the whole dataset on the host"
                    ),
                    symbol=tail,
                )


register(ThreadDisciplineAnalyzer())
