"""Span tracer: nested, attribute-carrying spans with thread-local
context.

Each finished span carries (name, span_id, parent_id, thread, wall_s,
attributes); nesting is tracked per-thread, so concurrent runs (or the
engine's prefetch worker) can never corrupt each other's parentage.
When annotation is on and jax is importable, every span also emits a
``jax.profiler.TraceAnnotation`` under the SAME ``deequ_tpu:<name>``
label — an XProf/TensorBoard trace and the in-repo timings share names,
so a kernel-level investigation and a span report line up 1:1.

The clock helpers here are the ONE sanctioned home of
``time.perf_counter`` — hot-path modules must route timing through this
layer (enforced by tools/telemetry_lint.py).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional

_span_ids = itertools.count(1)


def next_span_id() -> int:
    """A fresh process-unique span id (synthetic spans, replay remaps)."""
    return next(_span_ids)


def clock() -> float:
    """Monotonic seconds — the sanctioned timing source for callers
    outside the telemetry layer (see tools/telemetry_lint.py)."""
    return time.perf_counter()


def epoch() -> float:
    """Epoch seconds — the sanctioned wall-clock source (span
    ``started_at`` ordering; service layers use injected clocks and
    never call this directly)."""
    return time.time()


@dataclass(frozen=True)
class TraceContext:
    """The identity a run's spans share across threads and processes.

    ``trace_id`` names the run; ``span_id`` is the id RESERVED for the
    run's synthetic root span (emitted at terminal), so spans started
    anywhere under this context parent to the root before the root
    itself exists. ``process`` tags spans for fleet-timeline merges of
    per-host JSONL artifacts."""

    trace_id: str
    span_id: int
    process: str = ""

    @classmethod
    def mint(cls, seed: str = "", process: str = "") -> "TraceContext":
        suffix = uuid.uuid4().hex[:8]
        trace_id = f"{seed}-{suffix}" if seed else suffix
        return cls(trace_id=trace_id, span_id=next(_span_ids),
                   process=process)

    def child(self, span_id: int) -> "TraceContext":
        """The same trace re-anchored under ``span_id`` (what crosses
        the spawn boundary: the child's roots parent here)."""
        return TraceContext(trace_id=self.trace_id, span_id=span_id,
                            process=self.process)

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{self.process}"

    @classmethod
    def decode(cls, text: str) -> Optional["TraceContext"]:
        parts = text.split(":", 2)
        if len(parts) < 2:
            return None
        try:
            span_id = int(parts[1])
        except ValueError:
            return None
        return cls(trace_id=parts[0], span_id=span_id,
                   process=parts[2] if len(parts) > 2 else "")


@dataclass
class Span:
    name: str
    span_id: int
    parent_id: Optional[int]
    thread: str
    started_at: float  # epoch seconds (export ordering across threads)
    wall_s: float = 0.0
    attributes: Dict[str, Any] = field(default_factory=dict)
    trace_id: Optional[str] = None
    process: str = ""

    def set(self, **attrs: Any) -> "Span":
        self.attributes.update(attrs)
        return self

    def as_record(self) -> Dict[str, Any]:
        record = {
            "type": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "started_at": round(self.started_at, 6),
            "wall_s": round(self.wall_s, 6),
            "attributes": dict(self.attributes),
        }
        # trace identity only when a TraceContext was ambient — untraced
        # runs keep the classic record shape byte-for-byte
        if self.trace_id is not None:
            record["trace_id"] = self.trace_id
            if self.process:
                record["process"] = self.process
        return record


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()
    wall_s = 0.0

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self


NOOP_SPAN = _NoopSpan()
# reusable: nullcontext always returns its enter_result, so ONE instance
# serves every disabled span() call with zero allocation
NOOP_SPAN_CM = contextlib.nullcontext(NOOP_SPAN)


def _trace_annotation(name: str):
    """A jax TraceAnnotation for ``name``, or None when jax is absent
    (telemetry stays importable without an accelerator stack)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(f"deequ_tpu:{name}")
    except Exception:  # noqa: BLE001 — annotation is best-effort
        return None


class Tracer:
    """Thread-safe span context. Each thread owns its span stack; the
    finished-span callback is invoked on the finishing thread."""

    def __init__(self, annotate: bool = True):
        self.annotate = annotate
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_trace(self) -> Optional[TraceContext]:
        return getattr(self._local, "trace", None)

    @contextlib.contextmanager
    def trace_scope(self, ctx: Optional[TraceContext]) -> Iterator[None]:
        """Make ``ctx`` the ambient trace on this thread: spans started
        with an empty stack parent to ``ctx.span_id`` and every span
        carries ``ctx.trace_id`` until the scope exits."""
        prev = getattr(self._local, "trace", None)
        self._local.trace = ctx
        try:
            yield
        finally:
            self._local.trace = prev

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        on_finish: Optional[Callable[[Span], None]] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        stack = self._stack()
        ctx = getattr(self._local, "trace", None)
        sp = Span(
            name=name,
            span_id=next(_span_ids),
            parent_id=(
                stack[-1].span_id
                if stack
                else (ctx.span_id if ctx is not None else None)
            ),
            thread=threading.current_thread().name,
            started_at=time.time(),
            attributes=dict(attributes),
            trace_id=ctx.trace_id if ctx is not None else None,
            process=ctx.process if ctx is not None else "",
        )
        stack.append(sp)
        annotation = _trace_annotation(name) if self.annotate else None
        t0 = time.perf_counter()
        try:
            if annotation is None:
                yield sp
            else:
                with annotation:
                    yield sp
        finally:
            sp.wall_s = time.perf_counter() - t0
            # pop by identity: an exception while a child span is still
            # open must not mis-pop the parent
            if stack and stack[-1] is sp:
                stack.pop()
            elif sp in stack:
                stack.remove(sp)
            if on_finish is not None:
                on_finish(sp)


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace of the wrapped block into
    ``log_dir`` (open with TensorBoard's profile plugin / XProf).
    Span TraceAnnotations emitted inside the block appear in the dump
    under their ``deequ_tpu:<name>`` labels."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
