"""Torn-write-safe persistent XLA compilation cache store.

The stock jax file cache writes entries with a plain
``path.write_bytes(value)`` and reads them back with a blind
``read_bytes()``. A process killed mid-write (the crash-isolation
children of engine/subproc.py die by SIGKILL as a matter of course)
leaves a TRUNCATED entry that the next process happily deserializes —
the PR 12 ops note traced ApproxCountDistinct returning garbage
registers to exactly such a poisoned ``~/.cache/deequ_tpu_xla`` entry.

:class:`SafeCompilationCache` closes both holes:

- **Atomic writes** — ``put`` writes to a temp file in the cache
  directory and ``os.replace``-s it over the final name, so readers
  only ever observe no entry or a complete entry.
- **Validate-on-read** — ``get`` checks the entry actually decompresses
  (jax's value format is ``compress(4-byte compile time + serialized
  executable)``; zstandard when available, zlib otherwise) and meets
  the minimum length before returning it. A short/corrupt entry is
  unlinked and reported as a MISS — one recompile — with an
  ``engine.compile_cache_corrupt`` counter and a
  ``compile_cache_corrupt`` telemetry event, instead of feeding XLA a
  torn executable.
- **Cross-process lock** — an ``fcntl.flock`` on ``<dir>/.deequ_tpu.lock``
  brackets each read-validate-unlink and probe-then-replace sequence,
  so two processes racing the same key can't interleave a validation
  read with a concurrent replace.

:func:`install` swaps this store into jax's module-level cache slot
under jax's own initialization mutex. It is deliberately defensive: if
the (private) internals moved in a newer jax, installation reports
failure and the stock cache stays in place — the cache is an
optimization, never a correctness dependency.
"""

from __future__ import annotations

import os
import tempfile
import zlib
from typing import Optional

try:  # the same optional dependency jax itself compresses with
    import zstandard  # type: ignore
except ImportError:  # pragma: no cover - env without zstandard
    zstandard = None

#: zstd frame magic — distinguishes which codec wrote an entry, so a
#: zlib-written entry from an older process still validates here
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

#: compressed payload smaller than this cannot hold even the 4-byte
#: compile-time header; zlib's minimal stream is 8 bytes
_MIN_ENTRY_BYTES = 8

_LOCK_NAME = ".deequ_tpu.lock"


def _decompress(data: bytes) -> bytes:
    if data[:4] == _ZSTD_MAGIC:
        if zstandard is None:
            raise ValueError("zstd entry but no zstandard module")
        return zstandard.ZstdDecompressor().decompress(data)
    return zlib.decompress(data)


def _validate(data: Optional[bytes]) -> bool:
    """True iff ``data`` is a structurally complete cache entry: long
    enough, decompresses cleanly, and the plaintext holds at least the
    4-byte compile-time header."""
    if data is None or len(data) < _MIN_ENTRY_BYTES:
        return False
    try:
        plain = _decompress(data)
    except Exception:
        return False
    return len(plain) >= 4


class _FileLock:
    """``fcntl.flock`` context manager on a sidecar lock file. On
    platforms without fcntl (or an unlockable directory) it degrades to
    a no-op — atomic replace alone still prevents torn reads within a
    single key."""

    def __init__(self, path: str):
        self._path = path
        self._fd: Optional[int] = None

    def __enter__(self):
        try:
            import fcntl

            self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o644)
            fcntl.flock(self._fd, fcntl.LOCK_EX)
        except Exception:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
        return self

    def __exit__(self, *exc):
        if self._fd is not None:
            try:
                import fcntl

                fcntl.flock(self._fd, fcntl.LOCK_UN)
            except Exception:
                pass
            os.close(self._fd)
            self._fd = None
        return False


class SafeCompilationCache:
    """Duck-typed replacement for jax's file cache (``get``/``put`` +
    the ``_path`` attribute ``reset_cache`` reaches for)."""

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self._path = path

    def _entry_path(self, key: str) -> str:
        return os.path.join(self._path, key)

    def _lock(self) -> _FileLock:
        return _FileLock(os.path.join(self._path, _LOCK_NAME))

    def _report_corrupt(self, key: str, size: int) -> None:
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        tm.counter("engine.compile_cache_corrupt").inc()
        tm.event("compile_cache_corrupt", key=key, size_bytes=size)

    def get(self, key: str) -> Optional[bytes]:
        path = self._entry_path(key)
        with self._lock():
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                return None
            except OSError:
                return None
            if _validate(data):
                return data
            # torn/corrupt entry: drop it so the recompile's put heals
            # the cache, and surface the event for the ops report
            try:
                os.unlink(path)
            except OSError:
                pass
        self._report_corrupt(key, len(data) if data else 0)
        return None

    def put(self, key: str, value: bytes) -> None:
        path = self._entry_path(key)
        with self._lock():
            try:
                # keep an existing VALID entry (first writer wins, like
                # the stock cache's exists() probe) but let a fresh
                # compile overwrite a corrupt one
                with open(path, "rb") as f:
                    if _validate(f.read()):
                        return
            except OSError:
                pass
            fd, tmp = tempfile.mkstemp(
                dir=self._path, prefix=".tmp-" + key[:32] + "-"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(value)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass


def install(cache_dir: str) -> bool:
    """Swap :class:`SafeCompilationCache` into jax's module-level cache
    slot (under jax's own init mutex, with the initialized flag set so
    ``_initialize_cache`` never replaces it). Returns False — leaving
    the stock cache in charge — if jax's private internals have moved."""
    try:
        from jax._src import compilation_cache as cc

        with cc._cache_initialized_mutex:
            cc._cache = SafeCompilationCache(cache_dir)
            cc._cache_initialized = True
        return True
    except Exception:
        return False
