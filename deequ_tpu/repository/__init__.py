from deequ_tpu.repository.base import (
    AnalysisResult,
    InMemoryMetricsRepository,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.fs import FileSystemMetricsRepository
from deequ_tpu.repository.table import TableMetricsRepository

__all__ = [
    "AnalysisResult",
    "FileSystemMetricsRepository",
    "TableMetricsRepository",
    "InMemoryMetricsRepository",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
]
