from deequ_tpu.sketches.kll import KLLParameters, KLLSketchState

__all__ = ["KLLParameters", "KLLSketchState"]
