"""deequ_tpu — a TPU-native "unit tests for data" framework.

A brand-new data-quality framework with the capabilities of Deequ
(reference: ``jmscraig/deequ``, a Scala/Spark library — see SURVEY.md):
declarative checks evaluated against data-quality metrics, single-pass
scan-shared analyzer execution, mergeable incremental state, column
profiling, constraint suggestion, a persisted metrics repository, and
metric-series anomaly detection.

The execution engine is idiomatic JAX/XLA: analyzer states are fixed-shape
pytree commutative monoids, updates are vectorized masked reductions fused
by XLA into a single pass over device-resident column batches, merges are
collectives (psum / elementwise max / gather+recompress) over a
``jax.sharding.Mesh``. Upper layers (checks, constraints, repository,
anomaly detection, suggestion rules) are pure Python and engine-agnostic —
mirroring the reference's layering where everything above AnalysisRunner
never touches a DataFrame (SURVEY.md §1).
"""

from __future__ import annotations

import os

# int64/float64 support: states carry exact row counts (int64) and
# high-precision accumulators. On TPU, f64 is emulated — the engine's hot
# accumulation dtype is configurable (see deequ_tpu.config); finalization
# epilogues are tiny so f64 there is free.
if os.environ.get("DEEQU_TPU_NO_X64", "0") != "1":
    import jax

    jax.config.update("jax_enable_x64", True)

from deequ_tpu import config  # noqa: E402
from deequ_tpu.metrics import (  # noqa: E402
    DoubleMetric,
    Entity,
    HistogramMetric,
    KLLMetric,
    Metric,
)
from deequ_tpu.data import Dataset  # noqa: E402
from deequ_tpu.checks import Check, CheckLevel, CheckStatus  # noqa: E402
from deequ_tpu.verification import (  # noqa: E402
    VerificationResult,
    VerificationSuite,
)
from deequ_tpu.analyzers import (  # noqa: E402
    AnalysisRunner,
    AnalyzerContext,
    Applicability,
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    ColumnCount,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    CustomSql,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    RatioOfSums,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.engine import AnalysisEngine  # noqa: E402
from deequ_tpu.engine.deadline import (  # noqa: E402
    CancelToken,
    DeadlineExceeded,
    RunBudget,
    RunCancelled,
    ScanInterruption,
    install_graceful_shutdown,
)
from deequ_tpu.engine.resilience import (  # noqa: E402
    RetryPolicy,
    ScanDegradation,
    ScanStalled,
    TransientScanError,
)
from deequ_tpu.io.state_provider import (  # noqa: E402
    FileSystemStateProvider,
    InMemoryStateProvider,
    ScanCheckpointer,
)
from deequ_tpu.profiles.profiler import (  # noqa: E402
    ColumnProfiler,
    ColumnProfiles,
)
from deequ_tpu.profiles.runner import ColumnProfilerRunner  # noqa: E402
from deequ_tpu.repository.base import (  # noqa: E402
    AnalysisResult,
    InMemoryMetricsRepository,
    MetricsRepository,
    ResultKey,
)
from deequ_tpu.repository.fs import FileSystemMetricsRepository  # noqa: E402
from deequ_tpu.repository.table import TableMetricsRepository  # noqa: E402
from deequ_tpu.suggestions.rules import DEFAULT_RULES  # noqa: E402
from deequ_tpu.suggestions.runner import (  # noqa: E402
    ConstraintSuggestionResult,
    ConstraintSuggestionRunner,
)
from deequ_tpu.anomalydetection.base import (  # noqa: E402
    AnomalyDetector,
    DataPoint,
)
from deequ_tpu.anomalydetection.strategies import (  # noqa: E402
    AbsoluteChangeStrategy,
    BatchNormalStrategy,
    OnlineNormalStrategy,
    RelativeRateOfChangeStrategy,
    SimpleThresholdStrategy,
)
from deequ_tpu.anomalydetection.seasonal import (  # noqa: E402
    HoltWinters,
    MetricInterval,
    SeasonalityModel,
    SeriesSeasonality,
)
from deequ_tpu.schema import (  # noqa: E402
    RowLevelSchema,
    RowLevelSchemaValidator,
)
from deequ_tpu.sketches.kll import KLLParameters  # noqa: E402
from deequ_tpu.utils.observe import (  # noqa: E402
    RunMetadata,
    profiler_trace,
)

__version__ = "0.2.0"

__all__ = [
    "AbsoluteChangeStrategy",
    "AnalysisEngine",
    "AnalysisResult",
    "AnalysisRunner",
    "AnalyzerContext",
    "AnomalyDetector",
    "Applicability",
    "ApproxCountDistinct",
    "ApproxQuantile",
    "ApproxQuantiles",
    "BatchNormalStrategy",
    "CancelToken",
    "Check",
    "CheckLevel",
    "CheckStatus",
    "ColumnCount",
    "ColumnProfiler",
    "ColumnProfilerRunner",
    "ColumnProfiles",
    "Completeness",
    "Compliance",
    "ConstraintSuggestionResult",
    "ConstraintSuggestionRunner",
    "Correlation",
    "CountDistinct",
    "CustomSql",
    "DEFAULT_RULES",
    "DataPoint",
    "DataType",
    "DeadlineExceeded",
    "Dataset",
    "Distinctness",
    "DoubleMetric",
    "Entity",
    "Entropy",
    "FileSystemMetricsRepository",
    "TableMetricsRepository",
    "FileSystemStateProvider",
    "Histogram",
    "HistogramMetric",
    "HoltWinters",
    "InMemoryMetricsRepository",
    "InMemoryStateProvider",
    "KLLMetric",
    "KLLParameters",
    "KLLSketch",
    "Maximum",
    "MaxLength",
    "Mean",
    "Metric",
    "MetricInterval",
    "MetricsRepository",
    "Minimum",
    "MinLength",
    "MutualInformation",
    "OnlineNormalStrategy",
    "PatternMatch",
    "RatioOfSums",
    "RelativeRateOfChangeStrategy",
    "ResultKey",
    "RetryPolicy",
    "RowLevelSchema",
    "RowLevelSchemaValidator",
    "RunBudget",
    "RunCancelled",
    "RunMetadata",
    "ScanCheckpointer",
    "ScanDegradation",
    "ScanInterruption",
    "ScanStalled",
    "TransientScanError",
    "install_graceful_shutdown",
    "SeasonalityModel",
    "profiler_trace",
    "SeriesSeasonality",
    "SimpleThresholdStrategy",
    "Size",
    "StandardDeviation",
    "Sum",
    "Uniqueness",
    "UniqueValueRatio",
    "VerificationResult",
    "VerificationSuite",
    "config",
]
