"""Column profiler tests: exact profile values on fixtures, string-type
promotion, histograms, KLL percentiles (reference test model:
ColumnProfilerRunnerTest — SURVEY.md §4)."""

import numpy as np
import pytest

from deequ_tpu import Dataset
from deequ_tpu.data.table import Kind
from deequ_tpu.profiles.profiler import (
    ColumnProfiler,
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.profiles.runner import ColumnProfilerRunner


@pytest.fixture(scope="module")
def mixed_ds():
    return Dataset.from_pydict(
        {
            "ints": [1, 2, 3, 4, 5, 6],
            "floats": [1.0, 2.0, 3.0, 4.0, 5.0, None],
            "cat": ["a", "b", "a", "a", "b", "a"],
            "numeric_strings": ["1", "2", "3", "4", "5", "6"],
            "mixed_strings": ["x", "2", "y", "z", "w", "v"],
        }
    )


@pytest.fixture(scope="module")
def profiles(mixed_ds):
    return ColumnProfiler.profile(mixed_ds)


class TestProfiles:
    def test_num_records(self, profiles):
        assert profiles.num_records == 6

    def test_numeric_profile_exact_values(self, profiles):
        p = profiles["ints"]
        assert isinstance(p, NumericColumnProfile)
        assert p.completeness == 1.0
        assert p.mean == pytest.approx(3.5)
        assert p.minimum == 1.0
        assert p.maximum == 6.0
        assert p.sum == 21.0
        assert p.std_dev == pytest.approx(np.std([1, 2, 3, 4, 5, 6]))
        assert p.data_type == Kind.INTEGRAL
        assert not p.is_data_type_inferred

    def test_nulls_in_completeness(self, profiles):
        p = profiles["floats"]
        assert p.completeness == pytest.approx(5 / 6)
        assert p.mean == pytest.approx(3.0)  # nulls excluded

    def test_string_histogram(self, profiles):
        p = profiles["cat"]
        assert isinstance(p, StandardColumnProfile)
        assert p.data_type == Kind.STRING
        assert p.histogram is not None
        assert p.histogram.values["a"].absolute == 4
        assert p.histogram.values["b"].absolute == 2
        assert p.histogram.values["a"].ratio == pytest.approx(4 / 6)

    def test_numeric_string_promotion(self, profiles):
        """All-numeric string column is profiled as numeric (reference:
        pass-2 casts a projected copy — SURVEY.md §3.3)."""
        p = profiles["numeric_strings"]
        assert isinstance(p, NumericColumnProfile)
        assert p.is_data_type_inferred
        assert p.data_type == Kind.INTEGRAL
        assert p.mean == pytest.approx(3.5)
        assert p.type_counts.get("Integral") == 6

    def test_mixed_string_not_promoted(self, profiles):
        p = profiles["mixed_strings"]
        assert not isinstance(p, NumericColumnProfile)
        assert p.data_type == Kind.STRING

    def test_approx_distinct(self, profiles):
        assert profiles["cat"].approximate_num_distinct_values == pytest.approx(
            2, abs=0.5
        )
        assert profiles["ints"].approximate_num_distinct_values == pytest.approx(
            6, abs=1.0
        )


class TestProfilerOptions:
    def test_restrict_to_columns(self, mixed_ds):
        result = ColumnProfiler.profile(
            mixed_ds, restrict_to_columns=["ints"]
        )
        assert set(result.profiles.keys()) == {"ints"}
        with pytest.raises(KeyError):
            ColumnProfiler.profile(mixed_ds, restrict_to_columns=["nope"])

    def test_low_cardinality_threshold_gates_histograms(self, mixed_ds):
        result = ColumnProfiler.profile(
            mixed_ds, low_cardinality_histogram_threshold=1
        )
        assert result["cat"].histogram is None

    def test_kll_profiling(self):
        ds = Dataset.from_pydict({"x": list(np.arange(1000.0))})
        result = ColumnProfiler.profile(ds, kll_profiling=True)
        p = result["x"]
        assert p.kll is not None
        assert p.approx_percentiles is not None
        assert len(p.approx_percentiles) == 99
        # median of 0..999 ~ 500
        assert p.approx_percentiles[49] == pytest.approx(500, abs=15)

    def test_empty_dataset(self):
        ds = Dataset.from_pydict({"x": []})
        result = ColumnProfiler.profile(ds)
        assert result.num_records == 0
        assert result["x"].completeness == 0.0


class TestRunnerBuilder:
    def test_runner_end_to_end(self, mixed_ds):
        result = (
            ColumnProfilerRunner()
            .on_data(mixed_ds)
            .restrict_to_columns(["ints", "cat"])
            .run()
        )
        assert set(result.profiles.keys()) == {"ints", "cat"}
        assert result["ints"].mean == pytest.approx(3.5)
