"""Device sort+segment grouping (analyzers/spill.py): the TPU-native
replacement for the host Arrow spill on high-cardinality single numeric
columns. The ground truth is the host path itself (device_spill_grouping
= False forces it), mirroring the reference's exact groupBy semantics."""

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.data import Dataset


def _metrics(dataset, analyzers, spill: bool):
    with config.configure(device_spill_grouping=spill):
        ctx = AnalysisRunner.do_analysis_run(dataset, analyzers)
    return {a: ctx.metric(a) for a in analyzers}


def _assert_paths_agree(dataset, analyzers):
    device = _metrics(dataset, analyzers, spill=True)
    host = _metrics(dataset, analyzers, spill=False)
    for a in analyzers:
        d, h = device[a].value, host[a].value
        assert d.is_success and h.is_success, (a, d, h)
        dv, hv = d.get(), h.get()
        if isinstance(dv, float):
            assert dv == pytest.approx(hv, rel=1e-9), a
        else:
            assert dv == hv, a


class TestDeviceSpillAgainstHost:
    def test_int_column_all_count_metrics(self):
        rng = np.random.default_rng(11)
        ids = rng.integers(0, 5_000, 20_000, dtype=np.int64)
        ids[::97] = np.iinfo(np.int64).max  # extreme values are legal keys
        ids[::101] = np.iinfo(np.int64).min
        ds = Dataset.from_pydict({"id": list(ids)})
        _assert_paths_agree(
            ds,
            [
                CountDistinct("id"),
                Uniqueness("id"),
                Distinctness("id"),
                UniqueValueRatio("id"),
                Entropy("id"),
            ],
        )

    def test_float_column_with_nulls_nan_negzero(self):
        vals = [1.5, -0.0, 0.0, float("nan"), float("nan"), None, 2.5, 1.5]
        ds = Dataset.from_pydict({"x": vals * 100})
        # host dictionary_encode groups NaN==NaN but keeps -0.0 and 0.0
        # distinct; the device path canonicalizes NaN bits to match
        _assert_paths_agree(
            ds, [CountDistinct("x"), Uniqueness("x"), Distinctness("x")]
        )

    def test_where_filter(self):
        rng = np.random.default_rng(5)
        ds = Dataset.from_pydict(
            {
                "id": list(rng.integers(0, 500, 4_000, dtype=np.int64)),
                "flag": list(rng.integers(0, 2, 4_000, dtype=np.int64)),
            }
        )
        _assert_paths_agree(
            ds,
            [
                CountDistinct("id", where="flag = 1"),
                Uniqueness("id", where="flag = 1"),
            ],
        )

    def test_histogram_includes_null_bin_and_topk(self):
        rng = np.random.default_rng(7)
        vals = rng.integers(0, 50, 5_000).astype(object)
        vals[::13] = None
        ds = Dataset.from_pydict({"v": list(vals)})
        device = _metrics(ds, [Histogram("v", max_detail_bins=10)], True)
        host = _metrics(ds, [Histogram("v", max_detail_bins=10)], False)
        d = device[Histogram("v", max_detail_bins=10)].value.get()
        h = host[Histogram("v", max_detail_bins=10)].value.get()
        assert d.number_of_bins == h.number_of_bins
        # top-10 bin COUNTS agree exactly (the k-th bin may tie-break to
        # a different equally-frequent key); keys common to both agree
        dd = {k: v.absolute for k, v in d.values.items()}
        hh = {k: v.absolute for k, v in h.values.items()}
        assert sorted(dd.values()) == sorted(hh.values())
        for k in set(dd) & set(hh):
            assert dd[k] == hh[k]

    def test_float32_labels_match_dense_path(self):
        import pyarrow as pa

        vals = np.array([1.1, 2.2, 1.1, 3.3] * 50, dtype=np.float32)
        ds = Dataset.from_arrow(pa.table({"x": pa.array(vals)}))
        h = Histogram("x")
        device = _metrics(ds, [h], True)[h].value.get()
        host = _metrics(ds, [h], False)[h].value.get()
        # keys decode in the column's OWN dtype: str(np.float32(1.1))
        # == "1.1", not the widened float64 repr "1.100000023841858"
        assert set(device.values) == set(host.values)
        assert {k: v.absolute for k, v in device.values.items()} == {
            k: v.absolute for k, v in host.values.items()
        }

    def test_empty_and_all_null(self):
        ds = Dataset.from_pydict({"x": [None, None, None]})
        with config.configure(device_spill_grouping=True):
            ctx = AnalysisRunner.do_analysis_run(ds, [CountDistinct("x")])
        # all rows null -> empty state -> failure metric, like the host path
        assert not ctx.metric(CountDistinct("x")).value.is_success


class TestSpillStateInterop:
    def test_device_state_merges_with_host_state(self):
        from deequ_tpu.analyzers.grouping import (
            FrequenciesAndNumRows,
            FrequencyPlan,
            compute_many_frequencies,
        )

        a = Dataset.from_pydict({"id": [1, 2, 2, 3]})
        b = Dataset.from_pydict({"id": [3, 4, 4, 5]})
        plan = FrequencyPlan(("id",), None, False)
        with config.configure(device_spill_grouping=True):
            fa = compute_many_frequencies(a, [plan])[plan]
        with config.configure(device_spill_grouping=False):
            fb = compute_many_frequencies(b, [plan])[plan]
        merged = FrequenciesAndNumRows.merge(fa, fb)
        assert merged.num_rows == 8
        assert merged.num_groups == 5
        got = {
            k: c for k, c in zip(merged.keys[:, 0], merged.counts)
        }
        assert got == {1: 1, 2: 2, 3: 2, 4: 2, 5: 1}

    def test_joint_multicolumn_spill_equals_host(self):
        """Two-column plans whose joint key space exceeds the dense
        budget but fits a u64 lane take the packed-joint-code device
        sort; results must equal the Arrow host path exactly."""
        rng = np.random.default_rng(17)
        a = rng.integers(0, 300, 6_000).astype(object)
        b = rng.integers(0, 300, 6_000).astype(object)
        a[::31] = None
        b[::17] = None
        ds = Dataset.from_pydict({"a": list(a), "b": list(b)})
        analyzers = [
            CountDistinct(["a", "b"]),
            Uniqueness(["a", "b"]),
            Distinctness(["a", "b"]),
            Entropy(["a", "b"]),
        ]
        # force the dense path out: joint (301*301 ~ 90k) > budget slots
        with config.configure(dense_grouping_budget_bytes=4 * 1024):
            device = _metrics(ds, analyzers, spill=True)
            host = _metrics(ds, analyzers, spill=False)
        for z in analyzers:
            d, h = device[z].value, host[z].value
            assert d.is_success and h.is_success, (z, d, h)
            assert d.get() == pytest.approx(h.get(), rel=1e-9), z

    def test_joint_spill_event_and_merge(self):
        from deequ_tpu.analyzers.grouping import (
            FrequenciesAndNumRows,
            FrequencyPlan,
            compute_many_frequencies,
        )

        x = Dataset.from_pydict({"a": [1, 1, 2], "b": [5, 5, 6]})
        y = Dataset.from_pydict({"a": [2, 3], "b": [6, 7]})
        plan = FrequencyPlan(("a", "b"), None, False)
        with config.configure(
            dense_grouping_budget_bytes=16,  # joint (4*4=16) > 4 slots
            device_spill_grouping=True,
        ):
            events = []
            fx = compute_many_frequencies(x, [plan], events=events)[plan]
            # the list also carries scan_phases events (the one-pass
            # collector runs the shared scan), so filter by shape
            assert any(
                e.get("path") == "device-sort-joint" for e in events
            ), events
        with config.configure(device_spill_grouping=False):
            fy = compute_many_frequencies(y, [plan])[plan]
        merged = FrequenciesAndNumRows.merge(fx, fy)
        got = {
            (k[0], k[1]): c
            for k, c in zip(merged.keys, merged.counts)
        }
        assert got == {(1, 5): 2, (2, 6): 2, (3, 7): 1}
        assert merged.num_rows == 5

    def test_sharded_spill_equals_single_device(self, cpu_mesh):
        """The hash-bucket all_to_all re-shard (SURVEY §7 hard part #1):
        a high-cardinality int column under an 8-device mesh must give
        exactly the single-device answer."""
        from deequ_tpu.engine import AnalysisEngine

        rng = np.random.default_rng(21)
        ids = rng.integers(0, 40_000, 64_000, dtype=np.int64)
        ids[::513] = np.iinfo(np.int64).max  # exercises the sentinel path
        vals = ids.astype(object)
        vals[::97] = None
        ds = Dataset.from_pydict({"id": list(vals)})
        analyzers = [
            CountDistinct("id"),
            Uniqueness("id"),
            Distinctness("id"),
            Entropy("id"),
            Histogram("id", max_detail_bins=20),
        ]
        single = AnalysisRunner.do_analysis_run(ds, analyzers)
        meshed = AnalysisRunner.do_analysis_run(
            ds, analyzers, engine=AnalysisEngine(mesh=cpu_mesh)
        )
        for a in analyzers[:4]:
            assert meshed.metric(a).value.get() == pytest.approx(
                single.metric(a).value.get(), rel=1e-9
            ), a
        hs = single.metric(analyzers[4]).value.get()
        hm = meshed.metric(analyzers[4]).value.get()
        assert hs.number_of_bins == hm.number_of_bins
        assert sorted(
            v.absolute for v in hs.values.values()
        ) == sorted(v.absolute for v in hm.values.values())

    def test_sharded_state_persists_and_reloads(self, cpu_mesh, tmp_path):
        """A ShardedDeviceFrequencies state round-trips through the
        FileSystemStateProvider like any dense-path state."""
        from deequ_tpu import FileSystemStateProvider
        from deequ_tpu.engine import AnalysisEngine

        rng = np.random.default_rng(41)
        ds = Dataset.from_pydict(
            {"id": list(rng.integers(0, 2_000, 8_000, dtype=np.int64))}
        )
        a = CountDistinct("id")
        provider = FileSystemStateProvider(str(tmp_path))
        ctx = AnalysisRunner.do_analysis_run(
            ds, [a], engine=AnalysisEngine(mesh=cpu_mesh),
            save_states_with=provider,
        )
        want = ctx.metric(a).value.get()
        reloaded = provider.load(a)
        assert reloaded is not None
        assert a.compute_metric_from_state(reloaded).value.get() == want

    def test_sharded_spill_with_where_filter(self, cpu_mesh):
        from deequ_tpu.engine import AnalysisEngine

        rng = np.random.default_rng(33)
        ds = Dataset.from_pydict(
            {
                "id": list(rng.integers(0, 4_000, 16_000, dtype=np.int64)),
                "flag": list(rng.integers(0, 2, 16_000, dtype=np.int64)),
            }
        )
        analyzers = [
            CountDistinct("id", where="flag = 1"),
            Uniqueness("id", where="flag = 1"),
        ]
        single = AnalysisRunner.do_analysis_run(ds, analyzers)
        meshed = AnalysisRunner.do_analysis_run(
            ds, analyzers, engine=AnalysisEngine(mesh=cpu_mesh)
        )
        for a in analyzers:
            assert meshed.metric(a).value.get() == pytest.approx(
                single.metric(a).value.get(), rel=1e-9
            ), a

    def test_spill_event_recorded_in_run_metadata(self):
        # key range must exceed DENSE_DOMAIN_RANGE: bounded-domain
        # integers now (r5) ride the dense fused scan instead
        rng = np.random.default_rng(3)
        ds = Dataset.from_pydict(
            {"id": list(rng.integers(0, 10**7, 1_000, dtype=np.int64))}
        )
        with config.configure(device_spill_grouping=True):
            ctx = AnalysisRunner.do_analysis_run(ds, [Uniqueness("id")])
        events = ctx.run_metadata.events
        assert any(
            e["event"] == "grouping_spill" and e["path"] == "device-sort"
            for e in events
        )


class TestR4JointExtensions:
    """r4 (VERDICT r3 next #7): joint key spaces past one u64 lane ride
    TWO sort lanes; high-cardinality multi-column plans re-probe full
    cardinalities instead of falling to Arrow; f64 keys pack on the
    host where the backend lacks the 64-bit bitcast."""

    def test_two_lane_joint_exceeds_u64_equals_host(self):
        from deequ_tpu.analyzers import spill as spill_mod

        rng = np.random.default_rng(23)
        n = 60_000
        # four ~55k-cardinality columns: joint radix product
        # ~(55k)^4 ~ 1e19 > 2^62, needing the second sort lane
        cols = {
            f"c{j}": list(
                rng.integers(0, 500_000, n, dtype=np.int64)
            )
            for j in range(4)
        }
        ds = Dataset.from_pydict(cols)
        names = list(cols)
        # confirm the joint genuinely exceeds one u64 lane
        sizes = [
            len(ds.dictionary(c)) + 1 for c in names
        ]
        joint = 1
        for s in sizes:
            joint *= s
        assert joint >= 2**62
        assert spill_mod.split_joint_lanes(tuple(sizes)) is not None
        analyzers = [
            CountDistinct(names),
            Uniqueness(names),
            Distinctness(names),
            Entropy(names),
        ]
        with config.configure(dense_grouping_budget_bytes=4 * 1024):
            from deequ_tpu.analyzers.grouping import (
                FrequencyPlan,
                compute_many_frequencies,
            )

            events = []
            device = _metrics(ds, analyzers, spill=True)
            host = _metrics(ds, analyzers, spill=False)
            # path check: the plan takes the joint device sort
            plan = FrequencyPlan(tuple(names), None, False)
            compute_many_frequencies(ds, [plan], events=events)
            assert any(
                e.get("path") == "device-sort-joint" for e in events
            ), events
        for z in analyzers:
            d, h = device[z].value, host[z].value
            assert d.is_success and h.is_success, (z, d, h)
            assert d.get() == pytest.approx(h.get(), rel=1e-9), z

    def test_high_cardinality_pair_mutual_information(self):
        """Two columns whose cardinality blows the dense probe's budget
        must still ride the device joint path (full-cardinality
        re-probe), and MutualInformation must equal the Arrow oracle."""
        from deequ_tpu.analyzers import MutualInformation
        from deequ_tpu.analyzers.grouping import (
            FrequencyPlan,
            compute_many_frequencies,
        )

        rng = np.random.default_rng(29)
        n = 40_000
        a = rng.integers(0, 30_000, n, dtype=np.int64)
        b = np.where(
            rng.random(n) < 0.5, a, rng.integers(0, 30_000, n)
        )
        ds = Dataset.from_pydict({"a": list(a), "b": list(b)})
        analyzers = [
            MutualInformation(["a", "b"]),
            Uniqueness(["a", "b"]),
        ]
        with config.configure(dense_grouping_budget_bytes=1024):
            events = []
            plan = FrequencyPlan(("a", "b"), None, False)
            compute_many_frequencies(ds, [plan], events=events)
            assert any(
                e.get("path") == "device-sort-joint" for e in events
            ), events
            device = _metrics(ds, analyzers, spill=True)
            host = _metrics(ds, analyzers, spill=False)
        for z in analyzers:
            assert device[z].value.get() == pytest.approx(
                host[z].value.get(), rel=1e-9
            ), z

    def test_host_f64_keys_match_device_builder(self):
        """host_f64_u64_keys (the TPU path's host twin) must produce
        bit-identical keys to the jitted f64 builder (the CPU device
        path) — divergence would make TPU and CPU group differently."""
        import jax.numpy as jnp

        from deequ_tpu.analyzers.spill import (
            _chunk_key_fn,
            host_f64_u64_keys,
        )

        rng = np.random.default_rng(31)
        vals = rng.normal(0, 1e300, 4096)
        vals[::5] = np.nan
        vals[::7] = -0.0
        vals[::11] = 0.0
        vals[::13] = np.inf
        mask = rng.random(4096) < 0.9
        rows = rng.random(4096) < 0.95
        for include_nulls in (False, True):
            dk, dns, dnn = _chunk_key_fn("f64", include_nulls)(
                jnp.asarray(vals), jnp.asarray(mask), jnp.asarray(rows)
            )
            hk, hns, hnn = host_f64_u64_keys(
                vals, mask, rows, include_nulls
            )
            assert (np.asarray(dk) == hk).all()
            assert int(dns) == hns and int(dnn) == hnn

    def test_split_joint_lanes(self):
        from deequ_tpu.analyzers.spill import split_joint_lanes

        assert split_joint_lanes((10, 10)) == 2  # fits one lane
        big = 2**40
        assert split_joint_lanes((big, big)) == 1  # needs two lanes
        assert split_joint_lanes((big, big, big, big)) is None
        assert split_joint_lanes((2**63,)) is None  # single digit too big

    def test_meshed_joint_spill_equals_host(self, cpu_mesh):
        """r4: meshed multi-column joint spills ride the hash-bucket
        all_to_all shuffle (single-u64-lane joints) instead of falling
        to host Arrow — metrics must equal the Arrow oracle exactly."""
        from deequ_tpu.analyzers import MutualInformation
        from deequ_tpu.analyzers.grouping import (
            FrequencyPlan,
            compute_many_frequencies,
        )
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(41)
        n = 24_000
        a = rng.integers(0, 3_000, n, dtype=np.int64)
        b = np.where(rng.random(n) < 0.5, a, rng.integers(0, 3_000, n))
        ds = Dataset.from_pydict({"a": list(a), "b": list(b)})
        analyzers = [
            CountDistinct(["a", "b"]),
            Uniqueness(["a", "b"]),
            Entropy(["a", "b"]),
            MutualInformation(["a", "b"]),
        ]
        engine = AnalysisEngine(mesh=cpu_mesh, batch_size=n)
        with config.configure(dense_grouping_budget_bytes=1024):
            events = []
            plan = FrequencyPlan(("a", "b"), None, False)
            compute_many_frequencies(
                ds, [plan], engine=engine, events=events
            )
            assert any(
                e.get("path") == "device-sort-joint" for e in events
            ), events
            with config.configure(device_spill_grouping=True):
                ctx_mesh = AnalysisRunner.do_analysis_run(
                    ds, analyzers, engine=engine
                )
            with config.configure(device_spill_grouping=False):
                ctx_host = AnalysisRunner.do_analysis_run(ds, analyzers)
        for z in analyzers:
            d, h = ctx_mesh.metric(z).value, ctx_host.metric(z).value
            assert d.is_success and h.is_success, (z, d, h)
            assert d.get() == pytest.approx(h.get(), rel=1e-9), z

    def test_meshed_f64_host_bits_equals_host(self, cpu_mesh, monkeypatch):
        """r4: meshed f64 grouping via host-packed canonical bits (the
        TPU path, forced on via the test hook so the CPU mesh can
        exercise it) must equal the Arrow oracle — incl. NaN payloads
        and -0.0."""
        from deequ_tpu.analyzers import spill as spill_mod
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(43)
        n = 16_000
        vals = rng.normal(0, 1, n)
        vals[::7] = np.nan
        vals[::11] = -0.0
        vals[::13] = 0.0
        arr = vals.astype(object)
        arr[::17] = None
        ds = Dataset.from_pydict({"f": list(arr)})
        analyzers = [
            CountDistinct("f"),
            Uniqueness("f"),
            Distinctness("f"),
            Entropy("f"),
        ]
        monkeypatch.setattr(spill_mod, "_FORCE_HOST_F64_BITS", True)
        engine = AnalysisEngine(mesh=cpu_mesh, batch_size=n)
        with config.configure(device_spill_grouping=True):
            ctx_mesh = AnalysisRunner.do_analysis_run(
                ds, analyzers, engine=engine
            )
        # the device path must actually have run (not a vacuous
        # Arrow-vs-Arrow comparison)
        events = [
            e
            for e in (ctx_mesh.run_metadata.events or [])
            if e.get("event") == "grouping_spill"
        ]
        assert any(e["path"] == "device-sort" for e in events), events
        with config.configure(device_spill_grouping=False):
            ctx_host = AnalysisRunner.do_analysis_run(ds, analyzers)
        for z in analyzers:
            d, h = ctx_mesh.metric(z).value, ctx_host.metric(z).value
            assert d.is_success and h.is_success, (z, d, h)
            assert d.get() == pytest.approx(h.get(), rel=1e-9), z


class TestDenseDomainGate:
    """Bounded-domain integers (TPC-DS quantity shape) must ride the
    dense fused scan — the r5 range gate — with results equal to both
    the sort path it replaced and the host Arrow path."""

    def test_small_range_ints_stay_dense_and_exact(self):
        rng = np.random.default_rng(11)
        vals = rng.integers(1, 101, 50_000, dtype=np.int64)
        ds = Dataset.from_pydict({"q": list(vals)})
        with config.configure(device_spill_grouping=True):
            ctx = AnalysisRunner.do_analysis_run(
                ds, [Uniqueness("q"), CountDistinct("q")]
            )
        assert not any(
            e.get("event") == "grouping_spill"
            for e in ctx.run_metadata.events
        ), ctx.run_metadata.events
        with config.configure(device_spill_grouping=False):
            want = AnalysisRunner.do_analysis_run(
                ds, [Uniqueness("q"), CountDistinct("q")]
            )
        for a in (Uniqueness("q"), CountDistinct("q")):
            assert ctx.metric(a).value.get() == want.metric(a).value.get()


class TestMeshedTwoLaneJoint:
    def test_meshed_joint_exceeds_u64_equals_host(self, cpu_mesh):
        """Joint key spaces past one u64 lane (> 2^62) under a MESH
        (VERDICT r4 next #4): the hash-bucket all_to_all shuffle rides
        TWO key lanes with a per-shard lax.sort(num_keys=2); the
        count-family metrics must equal the host Arrow oracle exactly
        (the sharded two-lane fetch/decode path is pinned directly by
        test_meshed_two_lane_fetch_decodes_groups below — the only
        pairwise analyzer, MutualInformation, can never reach a
        > 2^62 joint)."""
        from deequ_tpu.analyzers import spill as spill_mod
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(29)
        n = 40_000
        # five ~38k-cardinality columns: joint radix product ~8e22,
        # well past one u64 lane
        cols = {
            f"c{j}": list(rng.integers(0, 500_000, n, dtype=np.int64))
            for j in range(5)
        }
        ds = Dataset.from_pydict(cols)
        names = list(cols)
        sizes = [len(ds.dictionary(c)) + 1 for c in names]
        joint = 1
        for s in sizes:
            joint *= s
        assert joint >= 2**62  # genuinely needs the second lane
        split = spill_mod.split_joint_lanes(tuple(sizes))
        assert split is not None and split < len(names)

        analyzers = [
            CountDistinct(names),
            Uniqueness(names),
            Distinctness(names),
            Entropy(names),
        ]
        engine = AnalysisEngine(mesh=cpu_mesh, batch_size=n)
        with config.configure(dense_grouping_budget_bytes=4 * 1024):
            with config.configure(device_spill_grouping=True):
                ctx_mesh = AnalysisRunner.do_analysis_run(
                    ds, analyzers, engine=engine
                )
            with config.configure(device_spill_grouping=False):
                ctx_host = AnalysisRunner.do_analysis_run(ds, analyzers)
        for z in analyzers:
            d, h = ctx_mesh.metric(z).value, ctx_host.metric(z).value
            assert d.is_success and h.is_success, (z, d, h)
            assert d.get() == pytest.approx(h.get(), rel=1e-9), z

    def test_meshed_two_lane_fetch_decodes_groups(self, cpu_mesh):
        """The sharded two-lane fetch path (keys + counts across
        shards) must reconstruct the exact group multiset."""
        from deequ_tpu.analyzers.grouping import (
            FrequencyPlan,
            compute_many_frequencies,
        )
        from deequ_tpu.analyzers import spill as spill_mod
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(30)
        n = 6_000
        cols = {
            f"c{j}": list(rng.integers(0, 400_000, n, dtype=np.int64))
            for j in range(5)
        }
        ds = Dataset.from_pydict(cols)
        names = tuple(cols)
        sizes = [len(ds.dictionary(c)) + 1 for c in names]
        joint = 1
        for s in sizes:
            joint *= s
        assert joint >= 2**62
        engine = AnalysisEngine(mesh=cpu_mesh, batch_size=n)
        plan = FrequencyPlan(names, None, False)
        with config.configure(
            dense_grouping_budget_bytes=1024, device_spill_grouping=True
        ):
            dev = compute_many_frequencies(ds, [plan], engine=engine)[
                plan
            ]
        assert isinstance(
            dev, spill_mod.ShardedTwoLaneDeviceFrequencies
        ), type(dev)
        with config.configure(device_spill_grouping=False):
            host = compute_many_frequencies(ds, [plan])[plan]
        got = sorted(
            (tuple(k), int(c)) for k, c in zip(dev.keys, dev.counts)
        )
        want = sorted(
            (tuple(k), int(c)) for k, c in zip(host.keys, host.counts)
        )
        assert got == want
