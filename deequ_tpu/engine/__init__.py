from deequ_tpu.engine.deadline import (
    CancelToken,
    DeadlineExceeded,
    RunBudget,
    RunCancelled,
    ScanInterrupted,
    ScanInterruption,
    install_graceful_shutdown,
)
from deequ_tpu.engine.scan import AnalysisEngine, monoid_all_reduce

__all__ = [
    "AnalysisEngine",
    "CancelToken",
    "DeadlineExceeded",
    "RunBudget",
    "RunCancelled",
    "ScanInterrupted",
    "ScanInterruption",
    "install_graceful_shutdown",
    "monoid_all_reduce",
]
