"""ApproxCountDistinct: HLL cardinality estimate.

Reference: ``analyzers/ApproxCountDistinct.scala`` + the
``StatefulHyperloglogPlus`` Catalyst aggregate (SURVEY.md §2.2/§2.3).
State = int8[2^14] registers; update = hash+clz+scatter-max inside the
shared fused scan; merge = elementwise max (mesh all-reduce / persisted
state merge). Nulls are ignored, matching the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import (
    Precondition,
    ScanOps,
    ScanShareableAnalyzer,
    has_column,
)
from deequ_tpu.analyzers.basic import _compile_where, _row_mask
from deequ_tpu.analyzers.states import ApproxCountDistinctState
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind
from deequ_tpu.metrics.metric import DoubleMetric
from deequ_tpu.sketches import hll


@dataclass(frozen=True)
class ApproxCountDistinct(ScanShareableAnalyzer):
    column: str
    where: Optional[str] = None

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column)]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        kind = dataset.schema.kind_of(self.column)
        value_repr = "codes" if kind == Kind.STRING else "values"
        return [
            ColumnRequest(self.column, value_repr),
            ColumnRequest(self.column, "mask"),
        ] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        from deequ_tpu.analyzers.base import pad_pow2

        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column
        kind = dataset.schema.kind_of(col)

        def init() -> ApproxCountDistinctState:
            return ApproxCountDistinctState(
                np.zeros(hll.M, dtype=np.int8)
            )

        if kind == Kind.STRING:
            # hash LUTs as runtime inputs (pow2-padded): the compiled
            # scan is shared across datasets — see ScanOps.consts
            lut1_host, lut2_host = hll.dictionary_hash_pairs(
                dataset.dictionary(col)
            )
            consts = {"h1": pad_pow2(lut1_host), "h2": pad_pow2(lut2_host)}

            def registers_of(batch, c, mask, prev):
                lut1, lut2 = c["h1"], c["h2"]
                if lut1.shape[0] <= hll.PRESENCE_DICT_CAP:
                    # small dictionary: presence compare-reduce beats
                    # the per-row gather+scatter (sketches/hll.py)
                    return hll.registers_from_code_presence(
                        batch[f"{col}::codes"][None, :],
                        mask[None, :],
                        lut1[None, :],
                        lut2[None, :],
                    )[0]
                codes = jnp.clip(
                    batch[f"{col}::codes"], 0, lut1.shape[0] - 1
                )
                return hll.registers_from_hash_pair(
                    lut1[codes], lut2[codes], mask
                )

        else:
            consts = None

            def registers_of(batch, c, mask, prev):
                # adaptive C=1 group: sorted-dedup when the carried
                # registers say mid-cardinality (sketches/hll.py)
                return hll.numeric_registers_adaptive(
                    batch[f"{col}::values"][None, :],
                    mask[None, :],
                    prev[None, :],
                )[0]

        def update(state: ApproxCountDistinctState, batch, consts_in=None):
            mask = batch[f"{col}::mask"] & _row_mask(batch, where_fn)
            regs = registers_of(
                batch, consts_in, mask, state.registers
            )
            return ApproxCountDistinctState(
                jnp.maximum(state.registers, regs)
            )

        return ScanOps(
            init, update, ApproxCountDistinctState.merge, consts=consts
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None:
            return DoubleMetric.success(
                self.entity, "ApproxCountDistinct", self.instance, 0.0
            )
        return DoubleMetric.success(
            self.entity,
            "ApproxCountDistinct",
            self.instance,
            hll.estimate(np.asarray(state.registers)),
        )
