"""Durable write-ahead run journal: the service's restart story.

The queue and every ``RunHandle`` live in process memory — a SIGKILLed
daemon forgets every accepted run. The journal fixes that at the edge:
``submit`` appends a durable record BEFORE the ticket enters the queue
(write-ahead ordering), every lifecycle transition appends another, and
``VerificationService.recover()`` on a fresh process replays the log to
re-admit everything that never reached a terminal state. Scan POSITION
is not the journal's job — ``ScanCheckpointer`` cursors already persist
durably per plan token, so a re-admitted run resumes mid-scan for free.

Format: one record per blob under the journal directory (any
``io/storage.py`` backend — plain paths, ``file://``, ``mem://``),
keyed ``runlog-{seq:010d}.rec`` so lexicographic order IS append order.
Each blob is ``crc32-hex + "\\n" + json-body`` and is written with
``write_bytes(durable=True)`` (fsync + dir fsync on LocalStorage). A
record that fails the CRC or does not parse marks the torn tail of the
log: replay stops there — the records after a corruption have no
ordering guarantee — and the loss is bounded to transitions not yet
acknowledged, exactly a truncation.

Timing discipline: the journal never reads a clock. Anything temporal
in a record (deadline remaining, queue wait) is computed by the caller
on ITS injected clock and passed in as plain data — monotonic
timestamps would be meaningless across the process restart the journal
exists to survive.
"""

from __future__ import annotations

import json
import threading
import zlib
from typing import Any, Dict, Iterator, List, Optional

KEY_PREFIX = "runlog-"
KEY_SUFFIX = ".rec"

#: lifecycle transitions a record may carry. ``preempted`` / ``resumed``
#: bracket a checkpoint-conserving preemption (docs/SERVICE.md
#: "Preemption and autoscaling"): neither is terminal, so a service
#: killed between the two still sees the run in ``pending_runs()`` and
#: ``recover()`` resumes it from its cursor.
RECORD_TYPES = (
    "submitted",
    "started",
    "checkpoint",
    "preempted",
    "resumed",
    "terminal",
    # fleet failover (docs/SERVICE.md "Fleet failover"): an ``epoch``
    # record marks an ownership transition of this journal directory —
    # the replica that claimed it and under which lease epoch. Epoch
    # records carry no run_id, so they are invisible to
    # ``pending_runs()``; ``compact()`` keeps only the newest one (the
    # older transitions are history, not state).
    "epoch",
    # adoption write-ahead bracket: an ``adoption_intent`` lands
    # durably BEFORE this journal's owner CASes a claim on a dead
    # peer's lease chain, ``adoption_done`` after the orphan's runs
    # are all replayed (or the claim race was lost). An intent with no
    # matching done is a half-finished adoption — whoever adopts (or
    # recovers) THIS journal completes it via ``pending_adoptions()``,
    # because the claimed chain itself is terminal and never re-polled.
    "adoption_intent",
    "adoption_done",
)


def _encode(body: Dict[str, Any]) -> bytes:
    payload = json.dumps(body, sort_keys=True).encode()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return f"{crc:08x}\n".encode() + payload


def _decode(blob: bytes) -> Optional[Dict[str, Any]]:
    """The record body, or None for a torn/corrupt blob."""
    try:
        header, payload = blob.split(b"\n", 1)
        if int(header, 16) != (zlib.crc32(payload) & 0xFFFFFFFF):
            return None
        body = json.loads(payload)
    except Exception:  # noqa: BLE001 — any malformation = torn record
        return None
    return body if isinstance(body, dict) else None


class RunJournal:
    """Append-only durable journal over a storage backend. Thread-safe;
    one instance per service. Sequence numbers continue from whatever
    the directory already holds, so a recovered service appends to the
    same log it replays."""

    def __init__(self, path: str):
        from deequ_tpu.io.storage import storage_for

        self._path = path
        self._storage = storage_for(path)
        self._lock = threading.Lock()
        self._seq = self._scan_top_seq()

    @property
    def path(self) -> str:
        return self._path

    def _scan_top_seq(self) -> int:
        top = 0
        for key in self._storage.list_keys(KEY_PREFIX):
            digits = key[len(KEY_PREFIX):].split(".", 1)[0]
            try:
                top = max(top, int(digits))
            except ValueError:
                continue
        return top

    @staticmethod
    def _key(seq: int) -> str:
        return f"{KEY_PREFIX}{seq:010d}{KEY_SUFFIX}"

    # -- append side ------------------------------------------------------

    def append(self, record_type: str, run_id: str, **fields: Any) -> int:
        """Durably append one transition; returns its sequence number.
        ``fields`` must be JSON-safe (the caller owns that — exceptions
        are reduced to strings at the call site)."""
        if record_type not in RECORD_TYPES:
            raise ValueError(f"unknown journal record type {record_type!r}")
        body = {"type": record_type, "run_id": run_id, **fields}
        with self._lock:
            self._seq += 1
            seq = self._seq
            body["seq"] = seq
            blob = _encode(body)
            key = self._key(seq)
            try:
                self._storage.write_bytes(key, blob, durable=True)
            except TypeError:  # pre-``durable=`` Storage subclass
                self._storage.write_bytes(key, blob)
        return seq

    def record_submitted(self, run_id: str, **fields: Any) -> int:
        return self.append("submitted", run_id, **fields)

    def record_started(self, run_id: str, **fields: Any) -> int:
        return self.append("started", run_id, **fields)

    def record_checkpoint(self, run_id: str, **fields: Any) -> int:
        return self.append("checkpoint", run_id, **fields)

    def record_preempted(self, run_id: str, **fields: Any) -> int:
        """Written AFTER the victim's final checkpoint persisted and
        BEFORE its ticket re-enters the queue (write-ahead, same
        discipline as ``submitted``)."""
        return self.append("preempted", run_id, **fields)

    def record_resumed(self, run_id: str, **fields: Any) -> int:
        return self.append("resumed", run_id, **fields)

    def record_terminal(self, run_id: str, state: str, **fields: Any) -> int:
        return self.append("terminal", run_id, state=state, **fields)

    def record_epoch(
        self, replica: str, epoch: int, **fields: Any
    ) -> int:
        """Mark an ownership transition of this journal directory: the
        replica now holding it and under which lease epoch (written on
        registration and again by an adopter after it wins the lease
        CAS). Run-less on purpose: epoch records are provenance, not
        run state."""
        return self.append("epoch", "", replica=replica, epoch=int(epoch), **fields)

    def record_adoption_intent(
        self, replica: str, journal_dir: str, epoch: int, **fields: Any
    ) -> int:
        """Write-ahead of an adoption: this journal's owner is about
        to claim ``replica``'s lease chain at ``epoch`` and replay the
        journal at ``journal_dir``. Durable BEFORE the claim CAS, so a
        claim can never outlive the knowledge of what it was for."""
        return self.append(
            "adoption_intent",
            "",
            replica=replica,
            journal_dir=journal_dir,
            epoch=int(epoch),
            **fields,
        )

    def record_adoption_done(
        self, replica: str, epoch: int, status: str = "adopted",
        **fields: Any,
    ) -> int:
        """Close an adoption intent: the orphan's runs are all
        journaled here (``status="adopted"``), the claim race was lost
        (``"race_lost"``), or another replica finished it
        (``"finished"``)."""
        return self.append(
            "adoption_done",
            "",
            replica=replica,
            epoch=int(epoch),
            status=status,
            **fields,
        )

    # -- replay side ------------------------------------------------------

    def replay(self) -> List[Dict[str, Any]]:
        """Records in append order, stopping at the first torn/corrupt
        blob (truncation semantics: nothing after a corruption is
        trusted). Missing blobs likewise end the log."""
        out: List[Dict[str, Any]] = []
        for seq in self._ordered_seqs():
            raw = self._storage.read_bytes(self._key(seq))
            body = _decode(raw) if raw is not None else None
            if body is None:
                from deequ_tpu.telemetry import get_telemetry

                get_telemetry().event(
                    "journal_truncated", path=self._path, at_seq=seq
                )
                break
            out.append(body)
        return out

    def _ordered_seqs(self) -> Iterator[int]:
        seqs = []
        for key in self._storage.list_keys(KEY_PREFIX):
            digits = key[len(KEY_PREFIX):].split(".", 1)[0]
            try:
                seqs.append(int(digits))
            except ValueError:
                continue
        return iter(sorted(seqs))

    def pending_runs(self) -> Dict[str, Dict[str, Any]]:
        """run_id -> state for every journaled run WITHOUT a terminal
        record, in submit order: the submitted record's fields plus
        ``started`` (bool) and ``last_checkpoint`` (fields of the latest
        checkpoint record, or None), plus the preemption bracket:
        ``preempted`` (True while a preemption record is not yet
        matched by a ``resumed`` one), ``preempt_count``, and
        ``last_preemption`` (the latest preemption record's fields)."""
        pending: Dict[str, Dict[str, Any]] = {}
        for record in self.replay():
            run_id = record.get("run_id")
            rtype = record.get("type")
            if not run_id:
                continue
            if rtype == "submitted":
                entry = {
                    k: v
                    for k, v in record.items()
                    if k not in ("type", "seq")
                }
                entry["started"] = False
                entry["last_checkpoint"] = None
                entry["preempted"] = False
                entry["preempt_count"] = 0
                entry["last_preemption"] = None
                pending[run_id] = entry
            elif run_id in pending:
                if rtype == "started":
                    pending[run_id]["started"] = True
                elif rtype == "checkpoint":
                    pending[run_id]["last_checkpoint"] = {
                        k: v
                        for k, v in record.items()
                        if k not in ("type", "seq", "run_id")
                    }
                elif rtype == "preempted":
                    entry = pending[run_id]
                    entry["preempted"] = True
                    entry["preempt_count"] += 1
                    entry["last_preemption"] = {
                        k: v
                        for k, v in record.items()
                        if k not in ("type", "seq", "run_id")
                    }
                elif rtype == "resumed":
                    pending[run_id]["preempted"] = False
                elif rtype == "terminal":
                    del pending[run_id]
        return pending

    def pending_adoptions(self) -> List[Dict[str, Any]]:
        """Adoption intents with no matching done record, in append
        order — the half-finished adoptions a later adopter (or a
        ``recover()`` of this journal) must complete. Keyed by
        (orphan replica, claim epoch): a re-attempt of the same chain
        claims a HIGHER epoch, so it is its own intent."""
        intents: Dict[Any, Dict[str, Any]] = {}
        for record in self.replay():
            rtype = record.get("type")
            if rtype not in ("adoption_intent", "adoption_done"):
                continue
            key = (record.get("replica"), record.get("epoch"))
            if rtype == "adoption_intent":
                intents[key] = {
                    k: v
                    for k, v in record.items()
                    if k not in ("type", "seq", "run_id")
                }
            else:
                intents.pop(key, None)
        return list(intents.values())

    # -- maintenance ------------------------------------------------------

    def compact(self) -> int:
        """Drop the records of runs that reached a terminal state
        (their story is over; replay does not need them). Returns how
        many records were deleted. Corrupt-tail blobs are also dropped —
        after a replayed recovery they are dead weight."""
        records = self.replay()
        terminal = {
            r["run_id"]
            for r in records
            if r.get("type") == "terminal" and r.get("run_id")
        }
        # run-less epoch records would survive the terminal filter
        # forever (their run_id "" is never terminal); keep only the
        # newest — current ownership — and drop the history.
        epoch_seqs = [
            r["seq"]
            for r in records
            if r.get("type") == "epoch" and "seq" in r
        ]
        stale_epochs = set(epoch_seqs[:-1])
        # adoption brackets: a done record closes its intent — both
        # are history once matched. PENDING intents survive compaction
        # (they are exactly the state a later adopter must replay).
        done_keys = {
            (r.get("replica"), r.get("epoch"))
            for r in records
            if r.get("type") == "adoption_done"
        }
        stale_adoptions = {
            r["seq"]
            for r in records
            if r.get("type") in ("adoption_intent", "adoption_done")
            and "seq" in r
            and (r.get("replica"), r.get("epoch")) in done_keys
        }
        live_seqs = {
            r["seq"]
            for r in records
            if r.get("run_id") not in terminal
            and "seq" in r
            and r["seq"] not in stale_epochs
            and r["seq"] not in stale_adoptions
        }
        removed = 0
        with self._lock:
            for seq in list(self._ordered_seqs()):
                if seq not in live_seqs:
                    self._storage.delete(self._key(seq))
                    removed += 1
        return removed

    def __repr__(self) -> str:
        return f"RunJournal({self._path!r})"
