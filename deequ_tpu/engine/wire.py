"""Per-column wire codecs for the streamed packed wire.

The streamed path is bytes-bound: BENCH_r04's ``streaming_bundle_100m``
shows wall ≈ bytes/link exactly, while the host decode pipeline has
~10x headroom (docs/PERF.md "Wire diet"). Every byte NOT shipped is
therefore wall time recovered at link rate. This module decides, ONCE
per run, a per-column *wire* dtype narrower than the canonical batch
dtype wherever the data provably allows it:

- int64/int32/int16 values -> the narrowest signed int covering the
  column's range, from parquet row-group statistics
  (``dataset.integral_range``, free — no data scan) when available,
  else from a first-batch probe;
- float64 values -> float32 when a first-batch probe shows every value
  round-trips BIT-exactly (checked on integer views, so NaN payloads
  and signed zeros count); lossy columns stay f64;
- dictionary codes and utf8 lengths -> first-batch probe (their
  canonical dtypes are already range-shaped, but delta-mode codes ship
  canonical i32 and probe down to i8/i16 on the wire).

The decode back to the canonical dtype is folded into the fused
``wire_unpack`` (engine/scan.py), so device programs see canonical
dtypes bit-identically and plan fingerprints stay data-independent.

The decision is per RUN, never per batch — the fixed-layout
no-recompile contract documented on ``narrow_int64_values``. Batches
that violate a resolved codec (stats lied, a dictionary grew past the
probed width) raise :class:`CodecViolation` on the prefetch thread;
the pack loop widens the table (``CodecTable.widen`` — a version bump
the consumer answers by rebuilding the wire + fused jit under a new
plan key) and re-packs the SAME batch, so a violation costs one
retrace, never a wrong metric or a quarantine.

Every non-identity codec is guarded on EVERY batch (vectorized
min/max or a bitwise round-trip compare, on the prefetch thread where
it overlaps device compute): parquet statistics are trusted for the
decision but verified against the data, because a corrupt file's
stats are exactly as corrupt as its values.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "CodecViolation",
    "ColumnCodec",
    "CodecTable",
    "narrowest_int_dtype",
    "resolve_codecs",
]

_SIGNED_STEPS = (np.dtype(np.int8), np.dtype(np.int16),
                 np.dtype(np.int32), np.dtype(np.int64))


def narrowest_int_dtype(lo: int, hi: int) -> np.dtype:
    """Narrowest SIGNED integer dtype covering [lo, hi] — the one
    range->width rule, shared by the stats decision and the probe."""
    for dt in _SIGNED_STEPS:
        info = np.iinfo(dt)
        if lo >= info.min and hi <= info.max:
            return dt
    return np.dtype(np.int64)


class CodecViolation(Exception):
    """A batch's values do not fit the resolved wire dtype. Raised on
    the prefetch thread by :meth:`CodecTable.encode`; the pack loop
    answers with :meth:`CodecTable.widen` + a re-pack — never an
    iterator restart, never a quarantine (the data is FINE, the
    narrowing bet lost)."""

    def __init__(self, key: str, required: np.dtype):
        super().__init__(
            f"wire codec for {key!r} violated: batch requires "
            f"{np.dtype(required).name}"
        )
        self.key = key
        self.required = np.dtype(required)


@dataclass
class ColumnCodec:
    """One wire-key's codec: ``canonical`` is what the device program
    sees (decode target), ``wire`` what ships. ``wire is None`` means
    the decision is deferred to the first-batch probe; ``origin``
    records how the width was chosen ("stats" | "probe")."""

    key: str
    canonical: np.dtype
    wire: Optional[np.dtype]
    origin: str

    @property
    def active(self) -> bool:
        return self.wire is not None and self.wire != self.canonical


@dataclass
class CodecTable:
    """The run's resolved codec set, versioned: ``widen`` bumps
    ``version``, which invalidates wires/jits built against the old
    widths (the streaming loop keys its plan-cache entry and its
    sub-batch wires on ``token()``, which embeds the version)."""

    codecs: Dict[str, ColumnCodec] = field(default_factory=dict)
    version: int = 0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    def codec(self, key: str) -> Optional[ColumnCodec]:
        return self.codecs.get(key)

    def token(self) -> tuple:
        """Hashable fingerprint of the resolved table — appended to the
        streaming plan-cache key so a program traced against one codec
        set is never served to another (the plankey analyzer counts on
        this for ``config.wire_codecs`` coverage). Codecs-off runs
        produce the empty table's token, a distinct key."""
        return (
            self.version,
            tuple(
                (k, c.canonical.name,
                 None if c.wire is None else c.wire.name)
                for k, c in sorted(self.codecs.items())
            ),
        )

    def encode(self, key: str, values: np.ndarray) -> np.ndarray:
        """Encode one leaf for the wire (identity when no codec).
        Resolves a deferred probe on first sight; guards every resolved
        non-identity codec and raises :class:`CodecViolation` when the
        batch does not fit."""
        codec = self.codecs.get(key)
        if codec is None:
            return values
        wire = codec.wire
        if wire is None:
            wire = self._resolve_probe(codec, values)
        if wire == codec.canonical:
            return values
        if wire.kind == "i":
            if values.size:
                lo = int(values.min())
                hi = int(values.max())
                info = np.iinfo(wire)
                if lo < info.min or hi > info.max:
                    raise CodecViolation(
                        key, narrowest_int_dtype(lo, hi)
                    )
            return values.astype(wire)
        # float32 wire for a float64 canonical: ship only when every
        # value round-trips bit-exactly (integer views, so NaN
        # payloads/signed zeros are compared literally, not by ==)
        enc = values.astype(wire)
        if not np.array_equal(
            enc.astype(codec.canonical).view(np.int64),
            values.view(np.int64),
        ):
            raise CodecViolation(key, codec.canonical)
        return enc

    def _resolve_probe(
        self, codec: ColumnCodec, values: np.ndarray
    ) -> np.dtype:
        """First-batch probe: pick the wire dtype from the actual
        values (later batches are guarded; a violation widens)."""
        if codec.canonical.kind == "i":
            if values.size:
                wire = narrowest_int_dtype(
                    int(values.min()), int(values.max())
                )
            else:
                wire = np.dtype(np.int8)
            if wire.itemsize >= codec.canonical.itemsize:
                wire = codec.canonical
        else:
            enc = values.astype(np.float32)
            wire = (
                np.dtype(np.float32)
                if np.array_equal(
                    enc.astype(np.float64).view(np.int64),
                    values.view(np.int64),
                )
                else codec.canonical
            )
        with self._lock:
            if codec.wire is None:
                codec.wire = wire
                # resolution completes the table, it does not invalidate
                # anything built before the first batch — no version bump
        return codec.wire

    def widen(self, key: str, required: np.dtype) -> None:
        """A resolved codec's bet lost: widen its wire dtype to cover
        ``required`` (and everything the old width already carried),
        bump the version so wires/jits rebuild, and record the event —
        the fallback leg of the stats-based narrowing satellite."""
        from deequ_tpu.telemetry import get_telemetry

        with self._lock:
            codec = self.codecs[key]
            old = codec.wire
            new = np.dtype(required)
            if old is not None and old.kind == "i" and new.kind == "i":
                new = np.promote_types(old, new)
            if new.itemsize >= codec.canonical.itemsize:
                new = codec.canonical
            codec.wire = new
            self.version += 1
        get_telemetry().event(
            "wire_codec_widened",
            key=key,
            wire_from=None if old is None else old.name,
            wire_to=new.name,
            origin=codec.origin,
        )

    def raw_bytes_of(self, key: str, encoded: np.ndarray) -> int:
        """What this leaf would have cost at canonical width — the
        codecs-off wire's bytes, for the wire-diet counters."""
        codec = self.codecs.get(key)
        if codec is None or codec.wire is None:
            return encoded.nbytes
        return encoded.size * codec.canonical.itemsize


def resolve_codecs(dataset, requests, enabled: bool) -> CodecTable:
    """Decide the run's codec table from static metadata — parquet
    row-group statistics where present, deferred first-batch probes
    elsewhere. Touches NO data values. Disabled (or non-candidate
    columns): an empty/identity table, byte-identical to today's wire."""
    table = CodecTable()
    if not enabled:
        return table
    seen = set()
    for req in requests:
        key = req.key
        if key in seen or req.repr in ("mask", "u64bits"):
            continue
        seen.add(key)
        try:
            canonical = np.dtype(dataset.request_dtype(req))
        except Exception:  # noqa: BLE001 — unknown repr: no codec
            continue
        if canonical.kind == "i" and canonical.itemsize > 1:
            wire: Optional[np.dtype] = None
            origin = "probe"
            if req.repr == "values":
                rng = None
                probe = getattr(dataset, "integral_range", None)
                if probe is not None:
                    try:
                        rng = probe(req.column)
                    except Exception:  # noqa: BLE001 — stats optional
                        rng = None
                if rng is not None:
                    # lint-ok: wire-discipline: loop is over column
                    # REQUESTS at plan time — one decision per run
                    wire = narrowest_int_dtype(int(rng[0]), int(rng[1]))
                    origin = "stats"
                    if wire.itemsize >= canonical.itemsize:
                        continue  # stats prove no narrowing: no codec
            table.codecs[key] = ColumnCodec(key, canonical, wire, origin)
        elif canonical == np.float64 and req.repr == "values":
            table.codecs[key] = ColumnCodec(key, canonical, None, "probe")
    return table
