"""DP-mesh tests on the 8-virtual-CPU-device mesh: sharded execution must
equal single-device, and the explicit shard_map + monoid-all-reduce step
must compile and agree (SURVEY.md §4: the no-real-cluster multi-device
story)."""

import jax
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.engine import AnalysisEngine, monoid_all_reduce
from fixtures import big_numeric


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    StandardDeviation("x"),
]


def test_mesh_equals_single_device(cpu_mesh):
    data = big_numeric(50_000)
    ctx_single = AnalysisRunner.do_analysis_run(
        data, ANALYZERS, engine=AnalysisEngine()
    )
    ctx_mesh = AnalysisRunner.do_analysis_run(
        data,
        ANALYZERS,
        engine=AnalysisEngine(mesh=cpu_mesh, batch_size=8_192),
    )
    for analyzer in ANALYZERS:
        a = ctx_single.metric(analyzer).value.get()
        b = ctx_mesh.metric(analyzer).value.get()
        assert a == pytest.approx(b, rel=1e-9), analyzer


def test_explicit_shard_map_step(cpu_mesh):
    """The explicit-SPMD path: per-shard update + monoid all-reduce."""
    data = big_numeric(16_384)
    planned = [(a, a.make_ops(data)) for a in ANALYZERS]
    engine = AnalysisEngine(mesh=cpu_mesh)
    step = engine.build_sharded_step(data, planned, cpu_mesh)

    requests = [
        r for a, _ in planned for r in a.device_requests(data)
    ]
    (batch,) = list(data.device_batches(requests, 16_384))
    states = tuple(ops.init() for _, ops in planned)
    out_states = step(states, batch)

    ctx = AnalysisRunner.do_analysis_run(data, ANALYZERS)
    for (analyzer, _), state in zip(planned, out_states):
        metric = analyzer.compute_metric_from_state(jax.device_get(state))
        expected = ctx.metric(analyzer).value.get()
        assert metric.value.get() == pytest.approx(expected, rel=1e-9)
