from deequ_tpu.suggestions.rules import (
    DEFAULT_RULES,
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintRule,
    ConstraintSuggestion,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.suggestions.runner import (
    ConstraintSuggestionResult,
    ConstraintSuggestionRunBuilder,
    ConstraintSuggestionRunner,
)

__all__ = [
    "CategoricalRangeRule",
    "CompleteIfCompleteRule",
    "ConstraintRule",
    "ConstraintSuggestion",
    "ConstraintSuggestionResult",
    "ConstraintSuggestionRunBuilder",
    "ConstraintSuggestionRunner",
    "DEFAULT_RULES",
    "FractionalCategoricalRangeRule",
    "NonNegativeNumbersRule",
    "RetainCompletenessRule",
    "RetainTypeRule",
    "UniqueIfApproximatelyUniqueRule",
]
