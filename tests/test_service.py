"""Multi-tenant verification service (PR 7): queue discipline, tenant
quotas, deadline/cancel envelopes, shared caches, and the ScanPlan
compile/execute split — all scheduling behavior asserted on
``ManualClock`` fake time with stub executors (no device work unless a
test is explicitly about plans)."""

import threading
import time

import numpy as np
import pytest

from deequ_tpu.engine.deadline import (
    DeadlineExceeded,
    ManualClock,
    RunBudget,
    RunCancelled,
)
from deequ_tpu.service import (
    DatasetCache,
    PlanCache,
    Priority,
    QuotaExceeded,
    RunHandle,
    RunQueue,
    RunRequest,
    RunState,
    RunTicket,
    VerificationService,
)


def _ticket(
    tenant="acme",
    priority=Priority.STANDARD,
    budget=None,
    run_id="run-x",
    payload=None,
):
    handle = RunHandle(run_id, tenant, priority)
    return RunTicket(seq=0, handle=handle, payload=payload, budget=budget)


def _spin_until(predicate, timeout_s=10.0):
    """Real-time wait for a cross-thread condition (the clocks under
    test are fake; thread scheduling is not)."""
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


class _FakeInterruption:
    def __init__(self, kind, reason="stopped"):
        self.kind = kind
        self.reason = reason


class _FakeResult:
    """Duck-typed VerificationResult: only what the scheduler reads."""

    def __init__(self, interruption=None, telemetry=None):
        self.interruption = interruption
        self.telemetry = telemetry


class TestRunQueue:
    def test_priority_order_fifo_within_class(self):
        q = RunQueue(clock=ManualClock())
        batch = _ticket(priority=Priority.BATCH, run_id="b")
        std1 = _ticket(priority=Priority.STANDARD, run_id="s1")
        std2 = _ticket(priority=Priority.STANDARD, run_id="s2")
        inter = _ticket(priority=Priority.INTERACTIVE, run_id="i")
        for t in (batch, std1, std2, inter):
            q.push(t)
        order = [
            q.pop(should_stop=lambda: True).handle.run_id
            for _ in range(4)
        ]
        assert order == ["i", "s1", "s2", "b"]

    def test_reserved_worker_never_takes_batch(self):
        q = RunQueue(clock=ManualClock())
        q.push(_ticket(priority=Priority.BATCH, run_id="b"))
        assert q.pop(
            max_priority=Priority.INTERACTIVE, should_stop=lambda: True
        ) is None
        q.push(_ticket(priority=Priority.INTERACTIVE, run_id="i"))
        got = q.pop(
            max_priority=Priority.INTERACTIVE, should_stop=lambda: True
        )
        assert got is not None and got.handle.run_id == "i"
        # the batch ticket is still there for a general worker
        assert q.depth() == 1

    def test_pending_quota_rejects_at_push(self):
        q = RunQueue(clock=ManualClock(), tenant_max_pending=2)
        q.push(_ticket(tenant="acme", run_id="1"))
        q.push(_ticket(tenant="acme", run_id="2"))
        with pytest.raises(QuotaExceeded):
            q.push(_ticket(tenant="acme", run_id="3"))
        # another tenant is unaffected by acme's quota
        q.push(_ticket(tenant="globex", run_id="4"))
        assert q.depth() == 3

    def test_active_quota_skips_tenant_not_queue(self):
        q = RunQueue(clock=ManualClock(), tenant_max_active=1)
        first = _ticket(tenant="acme", run_id="a1")
        second = _ticket(tenant="acme", run_id="a2")
        other = _ticket(tenant="globex", run_id="g1")
        q.push(first)
        q.push(second)
        q.push(other)
        t1 = q.pop(should_stop=lambda: True)
        assert t1.handle.run_id == "a1"
        # acme is at its active quota: a2 (earlier seq) is SKIPPED and
        # globex's ticket runs instead — one tenant can't wedge the
        # queue
        t2 = q.pop(should_stop=lambda: True)
        assert t2.handle.run_id == "g1"
        q.task_done(t1)
        t3 = q.pop(should_stop=lambda: True)
        assert t3.handle.run_id == "a2"

    def test_deadline_expired_while_queued_rejected(self):
        clock = ManualClock()
        q = RunQueue(clock=clock)
        ticket = _ticket(
            budget=RunBudget(deadline_s=5.0, clock=clock), run_id="late"
        )
        q.push(ticket)  # budget starts here: queue wait burns deadline
        clock.advance(10.0)
        assert q.pop(should_stop=lambda: True) is None
        handle = ticket.handle
        assert handle.status == RunState.REJECTED and handle.done
        with pytest.raises(DeadlineExceeded):
            handle.result(timeout=0)
        assert q.depth() == 0

    def test_cancel_while_queued_dropped_at_pop(self):
        q = RunQueue(clock=ManualClock())
        ticket = _ticket(run_id="gone")
        q.push(ticket)
        ticket.handle.cancel("changed my mind")
        assert q.pop(should_stop=lambda: True) is None
        assert ticket.handle.status == RunState.CANCELLED
        with pytest.raises(RunCancelled, match="changed my mind"):
            ticket.handle.result(timeout=0)

    def test_drain_queued_terminates_with_reason(self):
        q = RunQueue(clock=ManualClock())
        tickets = [_ticket(run_id=f"r{i}") for i in range(3)]
        for t in tickets:
            q.push(t)
        assert q.drain_queued("sigterm: rollout") == 3
        for t in tickets:
            assert t.handle.status == RunState.CANCELLED
            with pytest.raises(RunCancelled, match="sigterm"):
                t.handle.result(timeout=0)
        assert q.depth() == 0

    def test_result_timeout_while_queued(self):
        q = RunQueue(clock=ManualClock())
        ticket = _ticket(run_id="waiting")
        q.push(ticket)
        with pytest.raises(TimeoutError):
            ticket.handle.result(timeout=0.01)


class TestServiceScheduling:
    """VerificationService with stub executors: real worker threads,
    fake scheduling clock."""

    def _request(self, tenant="acme", priority=Priority.STANDARD,
                 dataset_key="shared", deadline_s=None):
        return RunRequest(
            tenant=tenant,
            checks=(),
            dataset_key=dataset_key,
            dataset_factory=lambda: None,
            priority=priority,
            deadline_s=deadline_s,
        )

    def test_interactive_reserve_prevents_starvation(self):
        release = threading.Event()

        def execute(ticket):
            if ticket.payload.dataset_key == "block":
                assert release.wait(timeout=30)
            return _FakeResult()

        svc = VerificationService(
            workers=2, interactive_reserve=1,
            clock=ManualClock(), execute=execute,
            tenant_max_pending=0, tenant_max_active=0,
        ).start()
        try:
            # the ONE general worker gets occupied by a long batch run
            blocker = svc.submit(self._request(
                priority=Priority.BATCH, dataset_key="block"
            ))
            assert _spin_until(
                lambda: blocker.status == RunState.RUNNING
            )
            # a second batch run can only wait behind it
            parked = svc.submit(self._request(
                priority=Priority.BATCH, dataset_key="block"
            ))
            # the interactive run lands on the reserve worker and
            # finishes while both batch runs still hold/want the
            # general worker — no priority inversion
            quick = svc.submit(self._request(
                tenant="globex", priority=Priority.INTERACTIVE
            ))
            assert quick.wait(timeout=10)
            assert quick.status == RunState.DONE
            assert blocker.status == RunState.RUNNING
            assert parked.status == RunState.QUEUED
            release.set()
            assert blocker.wait(timeout=10)
            assert parked.wait(timeout=10)
            assert parked.status == RunState.DONE
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)

    def test_cancel_running_returns_partial_result(self):
        def execute(ticket):
            assert ticket.handle.cancel_token.wait(timeout=30)
            return _FakeResult(
                interruption=_FakeInterruption("cancelled", "client")
            )

        svc = VerificationService(
            workers=1, interactive_reserve=0,
            clock=ManualClock(), execute=execute,
        ).start()
        try:
            handle = svc.submit(self._request())
            assert _spin_until(
                lambda: handle.status == RunState.RUNNING
            )
            handle.cancel("client")
            assert handle.wait(timeout=10)
            # cancelled WHILE RUNNING: terminal CANCELLED, but the
            # partial result is still delivered (same contract as a
            # direct bounded run)
            assert handle.status == RunState.CANCELLED
            assert isinstance(handle.result(timeout=0), _FakeResult)
        finally:
            svc.stop(drain=False, timeout=10)

    def test_deadline_interruption_is_still_done(self):
        # a run that the ENGINE stopped at its deadline completed its
        # envelope: the service reports DONE with the partial result,
        # not CANCELLED
        svc = VerificationService(
            workers=1, interactive_reserve=0, clock=ManualClock(),
            execute=lambda t: _FakeResult(
                interruption=_FakeInterruption("deadline", "budget")
            ),
        ).start()
        try:
            handle = svc.submit(self._request(deadline_s=60.0))
            assert handle.wait(timeout=10)
            assert handle.status == RunState.DONE
        finally:
            svc.stop(drain=False, timeout=10)

    def test_executor_failure_lands_on_handle(self):
        def execute(ticket):
            raise ValueError("boom")

        svc = VerificationService(
            workers=1, interactive_reserve=0,
            clock=ManualClock(), execute=execute,
        ).start()
        try:
            handle = svc.submit(self._request())
            assert handle.wait(timeout=10)
            assert handle.status == RunState.FAILED
            with pytest.raises(ValueError, match="boom"):
                handle.result(timeout=0)
            # the worker survived the failure and serves the next run
            ok = svc.submit(self._request())
            assert ok.wait(timeout=10)
            assert ok.status == RunState.FAILED  # same stub raises
        finally:
            svc.stop(drain=False, timeout=10)

    def test_tenant_pending_quota_at_submit(self):
        release = threading.Event()

        def execute(ticket):
            assert release.wait(timeout=30)
            return _FakeResult()

        svc = VerificationService(
            workers=1, interactive_reserve=0,
            clock=ManualClock(), execute=execute,
            tenant_max_pending=1,
        ).start()
        try:
            svc.submit(self._request(tenant="acme"))
            with pytest.raises(QuotaExceeded):
                svc.submit(self._request(tenant="acme"))
            # other tenants unaffected
            svc.submit(self._request(tenant="globex"))
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)

    def test_drain_cancels_queued_lets_running_finish(self):
        release = threading.Event()

        def execute(ticket):
            assert release.wait(timeout=30)
            return _FakeResult()

        svc = VerificationService(
            workers=1, interactive_reserve=0,
            clock=ManualClock(), execute=execute,
        ).start()
        try:
            running = svc.submit(self._request())
            assert _spin_until(
                lambda: running.status == RunState.RUNNING
            )
            queued = svc.submit(self._request())
            drained = svc.drain("sigterm: deploy")
            assert drained == 1
            assert queued.status == RunState.CANCELLED
            with pytest.raises(RunCancelled, match="sigterm"):
                queued.result(timeout=0)
            # the running run is untouched by drain and finishes
            assert running.status == RunState.RUNNING
            release.set()
            assert running.wait(timeout=10)
            assert running.status == RunState.DONE
            # a drained service refuses new work
            with pytest.raises(RuntimeError):
                svc.submit(self._request())
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)

    def test_sigterm_token_drains_service(self):
        from deequ_tpu.engine.deadline import (
            reset_shutdown_token,
            shutdown_token,
        )

        release = threading.Event()

        def execute(ticket):
            assert release.wait(timeout=30)
            return _FakeResult()

        reset_shutdown_token()
        svc = VerificationService(
            workers=1, interactive_reserve=0,
            clock=ManualClock(), execute=execute,
        )
        try:
            svc.start(install_sigterm=True)
            running = svc.submit(self._request())
            assert _spin_until(
                lambda: running.status == RunState.RUNNING
            )
            queued = svc.submit(self._request())
            # what the installed SIGTERM handler does, minus the signal
            # plumbing: fire the process-wide shutdown token
            shutdown_token().cancel("sigterm: shutting down")
            assert _spin_until(lambda: queued.done)
            assert queued.status == RunState.CANCELLED
            release.set()
            assert running.wait(timeout=10)
            assert running.status == RunState.DONE
        finally:
            release.set()
            svc.stop(drain=False, timeout=10)
            reset_shutdown_token()

    def test_wait_idle_and_graceful_stop(self):
        svc = VerificationService(
            workers=2, interactive_reserve=1,
            clock=ManualClock(),
            execute=lambda t: _FakeResult(),
        ).start()
        handles = [svc.submit(self._request()) for _ in range(4)]
        svc.stop(drain=True, timeout=20)
        assert all(h.status == RunState.DONE for h in handles)
        assert not svc.scheduler.running


class TestDatasetCache:
    class _FakeDataset:
        def __init__(self, nbytes):
            self.nbytes = nbytes
            self.cleared = False

        def clear_device_cache(self):
            self.cleared = True

    @pytest.fixture(autouse=True)
    def _weigh_by_nbytes(self, monkeypatch):
        monkeypatch.setattr(
            "deequ_tpu.engine.scan.estimated_run_bytes",
            lambda ds, engine=None: ds.nbytes,
        )

    def test_lease_shares_one_handle(self):
        cache = DatasetCache(watermark_bytes=0)
        builds = []

        def factory():
            ds = self._FakeDataset(10)
            builds.append(ds)
            return ds

        a, hit_a = cache.lease("t", factory)
        b, hit_b = cache.lease("t", factory)
        assert a is b and not hit_a and hit_b
        assert len(builds) == 1
        snap = cache.snapshot()
        assert snap["entries"]["t"]["pins"] == 2
        cache.release("t")
        cache.release("t")
        assert cache.snapshot()["entries"]["t"]["pins"] == 0

    def test_watermark_evicts_lru_unpinned_only(self):
        cache = DatasetCache(watermark_bytes=100)
        a, _ = cache.lease("a", lambda: self._FakeDataset(60))
        cache.release("a")
        b, _ = cache.lease("b", lambda: self._FakeDataset(60))
        # a (unpinned LRU) was evicted to fit b under the watermark
        assert a.cleared
        assert "a" not in cache.snapshot()["entries"]
        # b stays pinned: adding c goes over watermark but never
        # evicts a leased handle
        c, _ = cache.lease("c", lambda: self._FakeDataset(60))
        assert not b.cleared
        assert cache.snapshot()["total_bytes"] == 120
        # releasing b makes it evictable; release() re-runs eviction
        cache.release("b")
        assert b.cleared
        assert not c.cleared
        assert cache.snapshot()["total_bytes"] == 60

    def test_clear_clears_device_caches(self):
        cache = DatasetCache(watermark_bytes=0)
        a, _ = cache.lease("a", lambda: self._FakeDataset(5))
        cache.clear()
        assert a.cleared
        assert cache.snapshot()["entries"] == {}


class TestPlanCacheLedger:
    def test_note_warmed_dedups(self):
        plans = PlanCache()
        plans.note_warmed(["t1", "t2"])
        plans.note_warmed(["t2", "t3", None])
        assert plans.warmed_tokens == ["t1", "t2", "t3"]

    def test_record_run_accounting(self):
        plans = PlanCache()
        plans.record_run(
            {"counters": {"engine.plan_cache.misses": 1}}
        )
        plans.record_run({"counters": {"engine.plan_cache.hits": 2}})
        plans.record_run(None)  # a run without telemetry still counts
        snap = plans.snapshot()
        assert snap["runs"] == 3
        assert snap["recompile_runs"] == 1
        assert snap["warm_runs"] == 1


class TestScanPlan:
    """The compile/execute split in engine/scan.py: plans are
    first-class, cacheable, and shareable."""

    def _pairs(self, ds, analyzers):
        # what the runner does before handing pairs to the engine:
        # vouch for each op's closure purity so the plan is cacheable
        from deequ_tpu.analyzers.base import (
            CACHE_TOKEN_AUTO,
            make_cache_token,
        )

        pairs = []
        for a in analyzers:
            ops = a.make_ops(ds)
            if ops.cache_token is CACHE_TOKEN_AUTO:
                ops.cache_token = make_cache_token(
                    a, ds, predicates=(getattr(a, "where", None),)
                )
            pairs.append((a, ops))
        return pairs

    def test_prepare_then_execute_matches_run_scan(self):
        from deequ_tpu import Dataset
        from deequ_tpu.analyzers import Maximum, Mean, Sum
        from deequ_tpu.engine import AnalysisEngine

        ds = Dataset.from_pydict(
            {"x": [float(i) for i in range(2000)]}
        )
        analyzers = [Mean("x"), Sum("x"), Maximum("x")]
        engine = AnalysisEngine()
        plan = engine.prepare_scan(ds, self._pairs(ds, analyzers))
        assert plan is not None
        assert plan.mode in ("resident", "streaming")
        assert plan.batch_size == 2000
        states = engine.execute_plan(plan, ds)
        reference = AnalysisEngine().run_scan(
            ds, self._pairs(ds, analyzers)
        )
        import jax

        flat = jax.tree_util.tree_leaves(states)
        ref_flat = jax.tree_util.tree_leaves(reference)
        assert len(flat) == len(ref_flat) > 0
        for got, want in zip(flat, ref_flat):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want)
            )

    def test_plan_is_reusable_and_cache_visible(self):
        from deequ_tpu import Dataset
        from deequ_tpu.analyzers import Sum
        from deequ_tpu.engine import AnalysisEngine
        from deequ_tpu.engine.scan import plan_cache_snapshot

        # a column name unique to this test -> a fresh structural key
        ds = Dataset.from_pydict(
            {"svc_plan_probe": [float(i) for i in range(512)]}
        )
        engine = AnalysisEngine()
        pairs = self._pairs(ds, [Sum("svc_plan_probe")])
        plan = engine.prepare_scan(ds, pairs)
        assert plan.cache_key is not None
        assert plan.token is not None
        assert not plan.compiled
        engine.execute_plan(plan, ds)
        # the jitted executable is now resident under the plan's token
        assert plan.compiled
        assert plan.token in plan_cache_snapshot()
        # resubmission: same plan object executes again as a warm hit
        engine.execute_plan(plan, ds)
        assert engine.plan_cache_hit
        # and a SEPARATE engine preparing the same structure shares it
        other = AnalysisEngine()
        again = other.prepare_scan(ds, pairs)
        assert again.cache_key == plan.cache_key
        assert again.compiled
        other.execute_plan(again, ds)
        assert other.plan_cache_hit

    def test_empty_prepare_is_none(self):
        from deequ_tpu import Dataset
        from deequ_tpu.engine import AnalysisEngine

        ds = Dataset.from_pydict({"x": [1.0]})
        engine = AnalysisEngine()
        assert engine.prepare_scan(ds, []) is None
        assert engine.run_scan(ds, []) == []

    def test_module_level_estimated_run_bytes(self):
        from deequ_tpu import Dataset
        from deequ_tpu.engine import AnalysisEngine
        from deequ_tpu.engine.scan import estimated_run_bytes

        ds = Dataset.from_pydict(
            {"x": [1.0, 2.0], "y": [3.0, 4.0]}
        )
        assert estimated_run_bytes(ds) == AnalysisEngine(
        ).estimated_run_bytes(ds) > 0

    def test_pallas_flag_flip_same_key_on_cpu(self):
        from deequ_tpu import Dataset, config
        from deequ_tpu.analyzers import ApproxCountDistinct
        from deequ_tpu.engine import AnalysisEngine
        from deequ_tpu.sketches import pallas_scatter

        with config.configure(pallas_scatter=True):
            if pallas_scatter.impl_token() != "xla":
                pytest.skip("pallas kernel available on this host")
        ds = Dataset.from_pydict(
            {"k": list(np.arange(256, dtype=np.int64))}
        )
        engine = AnalysisEngine()
        pairs = self._pairs(ds, [ApproxCountDistinct("k")])
        baseline = engine.prepare_scan(ds, pairs)
        with config.configure(pallas_scatter=True):
            flipped = engine.prepare_scan(ds, pairs)
        # the key carries the RESOLVED scatter impl token: flipping
        # the flag where the kernel can't run changes nothing, so the
        # warm plan is correctly reused
        assert flipped.cache_key == baseline.cache_key

    def test_hll_widening_flip_yields_distinct_entry(self):
        # the acceptance flag-flip: hll_dedup_widening changes the
        # pooled-HLL unit (runtime-gated lax.cond vs scatter-only), so
        # the same profile compiles under a DISTINCT plan-cache entry
        from deequ_tpu import Dataset, config
        from deequ_tpu.engine.scan import plan_cache_snapshot
        from deequ_tpu.profiles.profiler import ColumnProfiler

        rng = np.random.default_rng(7)
        ds = Dataset.from_pydict({
            "svc_flip_a": list(
                rng.integers(0, 1 << 40, 2048).astype(np.int64)
            ),
            "svc_flip_b": list(
                rng.integers(0, 1 << 40, 2048).astype(np.int64)
            ),
        })
        before = set(plan_cache_snapshot())
        with config.configure(hll_dedup_widening=True):
            ColumnProfiler.profile(ds)
        mid = set(plan_cache_snapshot())
        with config.configure(hll_dedup_widening=False):
            ColumnProfiler.profile(ds)
        after = set(plan_cache_snapshot())
        assert len(mid - before) >= 1
        assert len(after - mid) >= 1  # the flip compiled a NEW plan
        # and re-running under the first flag is warm (no new entries)
        with config.configure(hll_dedup_widening=True):
            ColumnProfiler.profile(ds)
        assert set(plan_cache_snapshot()) == after


class TestWarmPlans:
    def test_warm_plans_reports_tokens_then_idempotent(self):
        from tools.warmup import warm_plans

        schema = {"svc_warm_v": "float32"}
        report = warm_plans(
            schema, suite=False, batch_size=1024, nullable=(False,)
        )
        assert report["passes"] >= 1
        assert report["total_s"] >= 0
        assert len(report["tokens"]) >= 1
        again = warm_plans(
            schema, suite=False, batch_size=1024, nullable=(False,)
        )
        assert again["tokens"] == []  # everything already resident
        assert again["already_warm"] >= len(report["tokens"])

    def test_exact_suite_warmup_means_zero_recompiles(self):
        # the service's startup path: warm the EXACT production checks
        # against a synthetic dataset, then the real run's telemetry
        # shows plan-cache hits and zero misses
        from deequ_tpu import Check, CheckLevel, VerificationSuite
        from tools.warmup import synthetic_dataset, warm_plans

        schema = {"svc_zero_x": "float32"}
        check = (
            Check(CheckLevel.ERROR, "svc-zero")
            .is_complete("svc_zero_x")
            .is_non_negative("svc_zero_x")
        )
        warm_plans(
            schema, batch_size=1024, nullable=(False,),
            checks=[check], profile=False,
        )
        ds = synthetic_dataset(
            schema, rows=1024, nullable=False, wide_ints=False, seed=3
        )
        result = (
            VerificationSuite().on_data(ds).add_check(check).run()
        )
        counters = (result.telemetry or {}).get("counters", {})
        assert counters.get("engine.plan_cache.misses", 0) == 0
        assert counters.get("engine.plan_cache.hits", 0) >= 1


class TestObsReportServiceSection:
    def test_render_service_section(self):
        from tools.obs_report import render_service

        records = [
            {"type": "event", "event": "service_plans_warmed",
             "tokens": ["tok1", "tok2"]},
            {"type": "event", "event": "service_run_started",
             "run_id": "run-1", "tenant": "acme",
             "priority": "interactive", "queue_wait_s": 0.01},
            {"type": "event", "event": "service_run_started",
             "run_id": "run-2", "tenant": "globex",
             "priority": "batch", "queue_wait_s": 0.5},
            {"type": "event", "event": "service_run_finished",
             "run_id": "run-1", "tenant": "acme", "status": "success"},
            {"type": "event", "event": "service_run_finished",
             "run_id": "run-2", "tenant": "globex", "status": "success"},
            {"type": "event", "event": "service_run_rejected",
             "run_id": "run-3", "tenant": "acme",
             "reason": "deadline expired while queued"},
            {"type": "event", "event": "service_dataset_leased",
             "run_id": "run-1", "dataset_key": "orders",
             "cache_hit": False},
            {"type": "event", "event": "service_dataset_leased",
             "run_id": "run-2", "dataset_key": "orders",
             "cache_hit": True},
            {"type": "run_summary", "run_id": 1, "counters":
                {"engine.plan_cache.hits": 2,
                 "engine.plan_cache.misses": 1}},
        ]
        out = render_service(records)
        assert out.startswith("service:")
        assert "acme" in out and "globex" in out
        assert "rejected=1" in out
        assert "p50=" in out and "p99=" in out
        assert "hits=2 compiles=1" in out
        assert "warmed 2 plan(s)" in out
        assert "hits=1 placements=1 evictions=0" in out
        assert "deadline-expired while queued: 1" in out

    def test_render_service_empty_without_events(self):
        from tools.obs_report import render_service

        assert render_service([{"type": "span"}]) == ""


# --------------------------------------------------------------------------
# end-to-end run tracing: the service-side span tree (docs/OBSERVABILITY.md)
# --------------------------------------------------------------------------


def _trace_table():
    """Module-level dataset factory: pickles by reference, so traced
    requests survive the spawn boundary under ``isolated=True``."""
    from deequ_tpu.data import Dataset

    rng = np.random.default_rng(29)
    return Dataset.from_pydict(
        {
            "a": rng.integers(0, 50, 2_000, dtype=np.int64).tolist(),
            "b": rng.normal(5.0, 2.0, 2_000).tolist(),
        }
    )


class _TraceSink:
    """Capture every finished span record on the process telemetry."""

    def __init__(self):
        from deequ_tpu.telemetry import get_telemetry

        self.records = []
        self._tm = get_telemetry()

    def __enter__(self):
        self._tm.add_span_sink(self.records.append)
        return self.records

    def __exit__(self, *exc):
        self._tm.remove_span_sink(self.records.append)


def _trace_tree(records, trace_id):
    """(spans, root) of one trace; asserts it is a SINGLE connected
    tree — every span reaches one root."""
    spans = [r for r in records if r.get("trace_id") == trace_id]
    assert spans, f"no spans for trace {trace_id}"
    ids = {r["span_id"] for r in spans}
    roots = [r for r in spans if r.get("parent_id") not in ids]
    assert len(roots) == 1, [(r["name"], r["parent_id"]) for r in roots]
    return spans, roots[0]


class TestRunTracing:
    def _trace_of(self, records, handle):
        ids = {
            r["trace_id"]
            for r in records
            if r.get("trace_id", "").startswith(handle.run_id + "-")
        }
        assert len(ids) == 1, (handle.run_id, ids)
        return ids.pop()

    def test_worker_run_one_tree_stages_sum_to_wall(self):
        """The differential pin: a scheduler-worker run yields one
        connected tree under one trace_id, and the critical-path stage
        decomposition sums to the root wall within 5% on ManualClock."""
        from tools.trace_report import STAGES, _Tree, decompose, load_traces

        clock = ManualClock()

        def execute(ticket):
            clock.advance(3.0)
            return _FakeResult()

        svc = VerificationService(
            workers=1, clock=clock, execute=execute,
            tenant_max_pending=0, tenant_max_active=0, trace=True,
        ).start()
        try:
            with _TraceSink() as records:
                handle = svc.submit(
                    RunRequest(
                        tenant="acme", checks=(), dataset_key="d",
                        dataset_factory=lambda: None,
                        priority=Priority.STANDARD,
                    )
                )
                assert _spin_until(lambda: handle.done)
                assert _spin_until(
                    lambda: any(
                        r["name"] == "ticket" for r in records
                    )
                )
        finally:
            svc.stop(drain=False, timeout=30)
        trace_id = self._trace_of(records, handle)
        spans, root = _trace_tree(records, trace_id)
        assert root["name"] == "ticket"
        names = {r["name"] for r in spans}
        assert {"queue_wait", "execute"} <= names
        trees = {
            tid: _Tree(sp) for tid, sp in load_traces(records).items()
        }
        decomp = decompose(trace_id, trees)
        assert decomp["wall_s"] >= 3.0
        assert set(decomp["stages"]) <= set(STAGES)
        total = sum(decomp["stages"].values())
        assert abs(total - decomp["wall_s"]) <= 0.05 * decomp["wall_s"]

    def test_coalesced_group_member_traces_link_to_host(self):
        """Each member of a coalesced group gets its OWN connected
        tree; non-host members carry a ``coalesced_scan`` link span
        pointing into the host's execute span."""
        from deequ_tpu.analyzers import Completeness, Mean

        svc = VerificationService(
            workers=1, coalesce=True, coalesce_window_s=0.0, trace=True,
        )
        with _TraceSink() as records:
            handles = [
                svc.submit(
                    RunRequest(
                        tenant=f"t{i}",
                        checks=(),
                        required_analyzers=[Completeness("a"), Mean("b")],
                        dataset_key="shared/traced",
                        dataset_factory=_trace_table,
                        priority=Priority.BATCH,
                    )
                )
                for i in range(3)
            ]
            svc.start()
            try:
                results = [h.result(timeout=300) for h in handles]
            finally:
                svc.stop(drain=False, timeout=30)
        trace_ids = [self._trace_of(records, h) for h in handles]
        assert len(set(trace_ids)) == 3
        link_targets = []
        execute_traces = []
        for trace_id in trace_ids:
            spans, root = _trace_tree(records, trace_id)
            assert root["name"] == "ticket"
            names = {r["name"] for r in spans}
            if "execute" in names:
                execute_traces.append(trace_id)
            for r in spans:
                if r["name"] == "coalesced_scan":
                    attrs = r.get("attributes") or {}
                    link_targets.append(
                        (attrs.get("link_trace_id"),
                         attrs.get("link_span_id"))
                    )
        # ONE host ran the superset scan; the other two link into it
        assert len(execute_traces) == 1
        host_trace = execute_traces[0]
        host_spans, _ = _trace_tree(records, host_trace)
        host_execute = next(
            r for r in host_spans if r["name"] == "execute"
        )
        assert len(link_targets) == 2
        assert all(
            target == (host_trace, host_execute["span_id"])
            for target in link_targets
        )
        # the host tree carries the real engine spans
        host_names = {r["name"] for r in host_spans}
        assert "run:coalesced_analysis" in host_names or any(
            n.startswith("run:") for n in host_names
        )
        assert any(n.startswith("pass:") for n in host_names)
        # every member's sliced result is scoped to its own trace
        for handle, result, trace_id in zip(handles, results, trace_ids):
            assert result.telemetry["trace_id"] == trace_id

    def test_isolated_run_replays_child_spans_into_tree(self):
        """A spawn-child run is still ONE connected tree: the child's
        spans stream back, re-root under the parent's launch span, and
        carry the child process tag."""
        from deequ_tpu.analyzers import Completeness, Mean

        svc = VerificationService(
            workers=1, isolated=True, coalesce=False, trace=True,
        )
        with _TraceSink() as records:
            handle = svc.submit(
                RunRequest(
                    tenant="acme",
                    checks=(),
                    required_analyzers=[Completeness("a"), Mean("b")],
                    dataset_key="iso/traced",
                    dataset_factory=_trace_table,
                    priority=Priority.STANDARD,
                )
            )
            svc.start()
            try:
                result = handle.result(timeout=300)
            finally:
                svc.stop(drain=False, timeout=30)
        assert result.telemetry is not None
        trace_id = self._trace_of(records, handle)
        spans, root = _trace_tree(records, trace_id)
        assert root["name"] == "ticket"
        child_spans = [r for r in spans if r.get("process") == "child"]
        assert child_spans, "no child-process spans replayed"
        assert any(
            r["name"].startswith("run:") for r in child_spans
        )

    def test_endpoints_live_while_running(self):
        """/metrics and /healthz answer DURING a run — stdlib urllib,
        ephemeral port, no new deps."""
        import json as _json
        import urllib.request

        started = threading.Event()
        release = threading.Event()

        def execute(ticket):
            started.set()
            release.wait(10)
            return _FakeResult()

        svc = VerificationService(
            workers=1, clock=ManualClock(), execute=execute,
            tenant_max_pending=0, tenant_max_active=0,
            trace=True, metrics_port=0,
            slo_objectives="interactive=1.0,standard=5.0",
        ).start()
        try:
            assert svc.metrics_server is not None
            assert svc.metrics_server.port > 0
            handle = svc.submit(
                RunRequest(
                    tenant="acme", checks=(), dataset_key="d",
                    dataset_factory=lambda: None,
                    priority=Priority.STANDARD,
                )
            )
            assert started.wait(10)
            base = svc.metrics_server.url
            metrics = urllib.request.urlopen(
                base + "/metrics", timeout=10
            ).read().decode()
            assert "deequ_tpu_service_submitted" in metrics
            health = _json.loads(
                urllib.request.urlopen(
                    base + "/healthz", timeout=10
                ).read().decode()
            )
            assert health["status"] == "ok"
            assert health["workers"] >= 1
            assert "queue" in health and "breakers" in health
            assert "shed" in health
            assert set(health["slo"]["classes"]) == {
                "interactive", "standard",
            }
            release.set()
            assert _spin_until(lambda: handle.done)
        finally:
            release.set()
            svc.stop(drain=False, timeout=30)
        # the endpoint dies with the service — no leaked thread
        assert svc.metrics_server is None

    def test_zero_cost_when_trace_and_port_off(self):
        """Default config: no endpoint thread, no TraceContext, no span
        records at all from a stub service run."""
        def execute(ticket):
            return _FakeResult()

        svc = VerificationService(
            workers=1, clock=ManualClock(), execute=execute,
            tenant_max_pending=0, tenant_max_active=0,
        ).start()
        try:
            assert svc.metrics_server is None
            with _TraceSink() as records:
                handle = svc.submit(
                    RunRequest(
                        tenant="acme", checks=(), dataset_key="d",
                        dataset_factory=lambda: None,
                        priority=Priority.STANDARD,
                    )
                )
                assert _spin_until(lambda: handle.done)
                svc.wait_idle(timeout=10)
        finally:
            svc.stop(drain=False, timeout=30)
        assert records == []
