"""A small SQL-expression compiler for predicates over device columns.

The reference's ``Compliance`` analyzer and ``.where(...)`` filters take
arbitrary Spark SQL expression strings (reference:
``src/main/scala/com/amazon/deequ/analyzers/Compliance.scala``,
``checks/Check.scala``; SURVEY.md §2.2). deequ_tpu keeps that surface but
compiles the expression to pure JAX ops at plan time:

- numeric columns evaluate on their device ``values``;
- string comparisons become *dictionary-code* operations — equality/IN
  become host-side dictionary lookups producing code sets, LIKE/RLIKE
  become a host-side regex sweep over the (small) dictionary producing a
  device bool lookup table gathered by code. Strings never reach the TPU
  (SURVEY.md §7 hard part #3).

Three-valued logic follows SQL: comparisons involving NULL are NULL; a
row "complies" iff the predicate is TRUE (not NULL, not FALSE).

Supported grammar (r4 extends toward the reference's Spark SQL surface;
SURVEY.md §2.2 Compliance = "arbitrary SQL predicate"):

| form | notes |
|---|---|
| OR / AND / NOT | SQL three-valued logic |
| = == != <> < <= > >= | string orderings via shared lexicographic ranks |
| + - * / % , unary - | / and % by zero -> NULL |
| IS [NOT] NULL | |
| [NOT] IN (...) | string or numeric item lists |
| BETWEEN x AND y | |
| [NOT] LIKE 'pat%' / RLIKE 're' | host regex over the dictionary |
| CASE WHEN c THEN v ... [ELSE v] END | numeric/bool branch values |
| COALESCE(a, b, ...) | numeric/bool arguments |
| ABS(x) | |
| LENGTH(s) | also over TRIM/UPPER/... results |
| TRIM/LTRIM/RTRIM(s) | host transform over the dictionary |
| UPPER(s) / LOWER(s) | compose freely, e.g. UPPER(TRIM(s)) |
| SUBSTR/SUBSTRING(s, pos[, len]) | Spark 1-based semantics |
| CONCAT(...) | at most one column operand, literals around it |
| CAST(x AS INT/BIGINT/DOUBLE/...) | numeric targets; string operands parse per dictionary entry, unparseable -> NULL |
| ts_col <op> 'YYYY-MM-DD[ HH:MM:SS]' | date literal in the column's unit |
| DATE_ADD(ts_col, n) / DATE_SUB | shifts by whole days in the column's unit |
| DATEDIFF(a, b) | UTC-day difference; timestamp columns and/or date literals |
| literals | numbers, 'strings', TRUE/FALSE/NULL |

String functions never reach the device: they evaluate host-side over
the (small) column dictionary, composing into per-code lookup tables;
the device work stays a gather over codes (SURVEY.md §7 hard part #3).
Unsupported syntax fails at PLANNING time (PredicateParseError), which
the runner degrades to that analyzer's failure metric — never a crash
mid-scan.

Known not-yet-implemented vs full Spark SQL (documented, degrade
cleanly): string-valued CASE/COALESCE results, multi-column CONCAT,
CAST to STRING or of timestamps, timezone-aware date semantics
(DATEDIFF counts UTC days).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deequ_tpu.data.table import ColumnRequest, Dataset, Kind

# --------------------------------------------------------------------------
# Tokenizer
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<bq_ident>`[^`]+`)
  | (?P<op><=|>=|!=|<>|==|=|<|>|\+|-|\*|/|%|\(|\)|,)
  | (?P<ident>[A-Za-z_][A-Za-z_0-9.]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IS", "NULL", "IN", "BETWEEN", "LIKE", "RLIKE",
    "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END", "CAST", "AS",
}


@dataclass(frozen=True)
class Token:
    kind: str  # 'number' | 'string' | 'ident' | 'op' | 'kw'
    text: str


def tokenize(expression: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(expression):
        m = _TOKEN_RE.match(expression, pos)
        if not m:
            raise PredicateParseError(
                f"cannot tokenize {expression[pos:pos + 20]!r} in predicate"
            )
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        kind = m.lastgroup
        if kind == "bq_ident":
            tokens.append(Token("ident", text[1:-1]))
        elif kind == "ident" and text.upper() in _KEYWORDS:
            tokens.append(Token("kw", text.upper()))
        else:
            tokens.append(Token(kind, text))
    return tokens


class PredicateParseError(ValueError):
    pass


# --------------------------------------------------------------------------
# AST
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Node:
    pass


@dataclass(frozen=True)
class ColumnRef(Node):
    name: str


@dataclass(frozen=True)
class NumberLit(Node):
    value: float


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class BoolLit(Node):
    value: bool


@dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # 'NOT' | 'NEG'
    operand: Node


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # 'AND','OR','=','!=','<','<=','>','>=','+','-','*','/','%'
    left: Node
    right: Node


@dataclass(frozen=True)
class IsNull(Node):
    operand: Node
    negate: bool


@dataclass(frozen=True)
class InList(Node):
    operand: Node
    items: Tuple[Node, ...]
    negate: bool


@dataclass(frozen=True)
class Between(Node):
    operand: Node
    low: Node
    high: Node


@dataclass(frozen=True)
class Like(Node):
    operand: Node
    pattern: str
    regex: bool
    negate: bool


@dataclass(frozen=True)
class CaseWhen(Node):
    """CASE WHEN c1 THEN v1 [WHEN c2 THEN v2 ...] [ELSE v] END."""

    whens: Tuple[Tuple[Node, Node], ...]
    else_: Optional[Node]


@dataclass(frozen=True)
class Cast(Node):
    """CAST(expr AS type); numeric targets only (INT truncates toward
    zero; string operands parse per dictionary entry, unparseable ->
    NULL, Spark's cast semantics)."""

    operand: Node
    type_name: str  # 'INT' | 'BIGINT' | 'LONG' | 'FLOAT' | 'DOUBLE'


@dataclass(frozen=True)
class StarLit(Node):
    """The `*` inside COUNT(*) (aggregate expressions only)."""


@dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: Tuple[Node, ...]


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Token]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Token:
        tok = self.peek()
        if tok is None:
            raise PredicateParseError("unexpected end of predicate")
        self.pos += 1
        return tok

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Token]:
        tok = self.peek()
        if tok and tok.kind == kind and (text is None or tok.text == text):
            return self.next()
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        tok = self.accept(kind, text)
        if tok is None:
            got = self.peek()
            raise PredicateParseError(
                f"expected {text or kind}, got {got.text if got else 'EOF'!r}"
            )
        return tok

    def parse(self) -> Node:
        node = self.or_expr()
        if self.peek() is not None:
            raise PredicateParseError(
                f"trailing tokens starting at {self.peek().text!r}"
            )
        return node

    def or_expr(self) -> Node:
        node = self.and_expr()
        while self.accept("kw", "OR"):
            node = BinOp("OR", node, self.and_expr())
        return node

    def and_expr(self) -> Node:
        node = self.not_expr()
        while self.accept("kw", "AND"):
            node = BinOp("AND", node, self.not_expr())
        return node

    def not_expr(self) -> Node:
        if self.accept("kw", "NOT"):
            return UnaryOp("NOT", self.not_expr())
        return self.comparison()

    def comparison(self) -> Node:
        node = self.additive()
        tok = self.peek()
        if tok is None:
            return node
        if tok.kind == "op" and tok.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"==": "=", "<>": "!="}.get(tok.text, tok.text)
            return BinOp(op, node, self.additive())
        if tok.kind == "kw" and tok.text == "IS":
            self.next()
            negate = self.accept("kw", "NOT") is not None
            self.expect("kw", "NULL")
            return IsNull(node, negate)
        negate = False
        if tok.kind == "kw" and tok.text == "NOT":
            nxt = (
                self.tokens[self.pos + 1]
                if self.pos + 1 < len(self.tokens)
                else None
            )
            if nxt and nxt.kind == "kw" and nxt.text in ("IN", "LIKE", "RLIKE"):
                self.next()
                negate = True
                tok = self.peek()
        if tok and tok.kind == "kw" and tok.text == "IN":
            self.next()
            self.expect("op", "(")
            items = [self.additive()]
            while self.accept("op", ","):
                items.append(self.additive())
            self.expect("op", ")")
            return InList(node, tuple(items), negate)
        if tok and tok.kind == "kw" and tok.text == "BETWEEN":
            self.next()
            low = self.additive()
            self.expect("kw", "AND")
            high = self.additive()
            return Between(node, low, high)
        if tok and tok.kind == "kw" and tok.text in ("LIKE", "RLIKE"):
            self.next()
            pat = self.next()
            if pat.kind != "string":
                raise PredicateParseError(
                    f"{tok.text} expects a string pattern"
                )
            return Like(
                node,
                _unquote(pat.text),
                regex=tok.text == "RLIKE",
                negate=negate,
            )
        return node

    def additive(self) -> Node:
        node = self.multiplicative()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("+", "-"):
                self.next()
                node = BinOp(tok.text, node, self.multiplicative())
            else:
                return node

    def multiplicative(self) -> Node:
        node = self.unary()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("*", "/", "%"):
                self.next()
                node = BinOp(tok.text, node, self.unary())
            else:
                return node

    def unary(self) -> Node:
        if self.accept("op", "-"):
            return UnaryOp("NEG", self.unary())
        return self.primary()

    def primary(self) -> Node:
        tok = self.next()
        if tok.kind == "kw" and tok.text == "CAST":
            self.expect("op", "(")
            operand = self.or_expr()
            self.expect("kw", "AS")
            type_tok = self.next()
            if type_tok.kind != "ident":
                raise PredicateParseError(
                    f"CAST expects a type name, got {type_tok.text!r}"
                )
            self.expect("op", ")")
            return Cast(operand, type_tok.text.upper())
        if tok.kind == "kw" and tok.text == "CASE":
            whens: List[Tuple[Node, Node]] = []
            while self.accept("kw", "WHEN"):
                cond = self.or_expr()
                self.expect("kw", "THEN")
                whens.append((cond, self.or_expr()))
            if not whens:
                raise PredicateParseError(
                    "CASE requires at least one WHEN ... THEN branch"
                )
            else_ = self.or_expr() if self.accept("kw", "ELSE") else None
            self.expect("kw", "END")
            return CaseWhen(tuple(whens), else_)
        if tok.kind == "number":
            return NumberLit(float(tok.text))
        if tok.kind == "string":
            return StringLit(_unquote(tok.text))
        if tok.kind == "kw" and tok.text == "TRUE":
            return BoolLit(True)
        if tok.kind == "kw" and tok.text == "FALSE":
            return BoolLit(False)
        if tok.kind == "kw" and tok.text == "NULL":
            return NullLit()
        if tok.kind == "op" and tok.text == "(":
            node = self.or_expr()
            self.expect("op", ")")
            return node
        if tok.kind == "ident":
            if self.accept("op", "("):
                args: List[Node] = []
                if tok.text.upper() == "COUNT" and self.accept("op", "*"):
                    args.append(StarLit())  # COUNT(*) only
                    self.expect("op", ")")
                elif not self.accept("op", ")"):
                    args.append(self.or_expr())
                    while self.accept("op", ","):
                        args.append(self.or_expr())
                    self.expect("op", ")")
                return FuncCall(tok.text.upper(), tuple(args))
            return ColumnRef(tok.text)
        raise PredicateParseError(f"unexpected token {tok.text!r}")


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def parse_predicate(expression: str) -> Node:
    return _Parser(tokenize(expression)).parse()


def _validate_date_literal(text: str) -> None:
    """The ONE date-literal validation (plan time); comparison and
    DATEDIFF literals must accept/reject identically."""
    import datetime as _dt

    try:
        _dt.datetime.fromisoformat(text)
    except ValueError as exc:
        raise PredicateParseError(
            f"{text!r} is not a date/timestamp literal "
            "(YYYY-MM-DD[ HH:MM:SS])"
        ) from exc


def _sql_like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# --------------------------------------------------------------------------
# Compiler: AST -> (requests, traced eval over batch)
# --------------------------------------------------------------------------

# An evaluated expression: (values, valid) with SQL null semantics, or for
# booleans (truth, valid). `values` may be numeric or int32 codes tagged
# with the column whose dictionary they index.


@dataclass
class _Val:
    values: jnp.ndarray
    valid: jnp.ndarray
    is_bool: bool = False
    codes_of: Optional[str] = None  # column name whose dictionary applies
    # host-side string transform composed over the dictionary (TRIM/
    # UPPER/LOWER/SUBSTR chains): consumers build per-code LUTs from
    # transform(dict[i]) instead of dict[i]; None = raw values
    transform: Optional[Callable[[str], str]] = None
    # timestamp/date lane: ``ts_per_day`` = how many epoch units make
    # one UTC day (set for TIMESTAMP/date columns and DATE_ADD results;
    # 1 = day-valued). Comparisons convert string literals into this
    # unit, and mixed-unit lanes normalize to the finer unit.
    # ``ts_col`` names the source column when the values are its RAW
    # storage epochs (literal conversion then goes through the exact
    # Arrow cast); None for derived day-valued lanes.
    ts_col: Optional[str] = None
    ts_per_day: Optional[int] = None

    def view(self, value: str) -> str:
        return self.transform(value) if self.transform else value


class _PredicateData:
    """What predicate evaluation may touch: the schema (strong) and the
    dictionaries (weak — only string predicates dereference them, and
    only at trace time while the owning run holds the dataset)."""

    __slots__ = ("schema", "_ref")

    def __init__(self, schema, ref):
        self.schema = schema
        self._ref = ref

    def dictionary(self, column: str):
        dataset = self._ref()
        if dataset is None:  # pragma: no cover — contract violation
            raise RuntimeError(
                "string predicate outlived its dataset; string "
                "predicates are only traced while the owning run holds "
                "the data"
            )
        return dataset.dictionary(column)

    def arrow_type(self, column: str):
        """Storage type (timestamp predicates need the epoch unit)."""
        dataset = self._ref()
        if dataset is None:  # pragma: no cover — contract violation
            raise RuntimeError(
                "timestamp predicate outlived its dataset; it is only "
                "traced while the owning run holds the data"
            )
        return dataset._column_arrow_type(column)


class CompiledPredicate:
    """A predicate compiled against a dataset's schema + dictionaries.

    ``requests`` lists the device columns needed; ``evaluate(batch)`` is
    traceable and returns (truth: bool array, valid: bool array). A row
    complies iff truth & valid.
    """

    def __init__(
        self,
        node: Node,
        dataset: Dataset,
        columns_used: Sequence[str],
        requests: Sequence[ColumnRequest],
    ):
        import weakref

        self._node = node
        # WEAK reference: compiled predicates end up inside jitted
        # closures that the cross-run plan cache retains — a strong ref
        # would pin the whole Arrow table for the cache's lifetime. The
        # dataset is only dereferenced at TRACE time (schema lookups,
        # dictionary lookups for string predicates), which happens while
        # the owning run still holds the dataset.
        self._dataset_ref = weakref.ref(dataset)
        self._schema = dataset.schema
        self.columns_used = tuple(columns_used)
        self.requests = tuple(requests)
        # a predicate touching NO string and NO timestamp column
        # evaluates identically on any dataset with the same schema
        # kinds (no dictionary-derived constants and no unit-dependent
        # epoch literals get baked into its closure) — the engine's
        # plan cache may reuse compiled scans across datasets only then
        self.dataset_independent = all(
            dataset.schema.kind_of(c) not in (Kind.STRING, Kind.TIMESTAMP)
            for c in self.columns_used
        )

    @property
    def _dataset(self) -> "_PredicateData":
        # shim: schema strongly held (all a NUMERIC predicate touches,
        # incl. on re-trace after the origin dataset is gone);
        # dictionaries resolve through the weakref (string predicates
        # only — those are never in cached cross-dataset plans)
        return _PredicateData(self._schema, self._dataset_ref)

    def evaluate(self, batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        val = _eval(self._node, batch, self._dataset)
        truth, valid = _as_bool(val)
        return truth, valid

    def complies(self, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        truth, valid = self.evaluate(batch)
        return truth & valid


def compile_predicate(expression: str, dataset: Dataset) -> CompiledPredicate:
    # per-dataset compile cache: device_requests() and make_ops() both
    # compile the same expressions during planning
    cache = getattr(dataset, "_predicate_cache", None)
    if cache is None:
        cache = {}
        setattr(dataset, "_predicate_cache", cache)
    if expression in cache:
        return cache[expression]
    node = parse_predicate(expression)
    cols = sorted(_columns_of(node))
    schema = dataset.schema
    requests: List[ColumnRequest] = []
    for c in cols:
        if not schema.has_column(c):
            raise KeyError(f"predicate references unknown column '{c}'")
        kind = schema.kind_of(c)
        if kind == Kind.STRING:
            requests.append(ColumnRequest(c, "codes"))
        else:
            requests.append(ColumnRequest(c, "values"))
        requests.append(ColumnRequest(c, "mask"))
    for col in _length_columns_of(node):
        requests.append(ColumnRequest(col, "lengths"))
    # static type check NOW (make_ops/planning time) so a bad predicate
    # degrades to THAT analyzer's failure metric — a raise later, inside
    # the shared fused-scan trace, would poison every co-scheduled
    # analyzer in the pass
    _check_types(node, schema)
    compiled = CompiledPredicate(node, dataset, cols, requests)
    cache[expression] = compiled
    return compiled


def _check_types(node: Node, schema) -> str:
    """Static kind inference: returns 'string' | 'stringlit' | 'value' |
    'null'; raises PredicateParseError on string/numeric mixes that the
    runtime would otherwise hit mid-trace."""

    def kind_of(n: Node) -> str:
        if isinstance(n, ColumnRef):
            k = schema.kind_of(n.name)
            if k == Kind.STRING:
                return "string"
            if k == Kind.TIMESTAMP:
                return "timestamp"
            return "value"
        if isinstance(n, StringLit):
            return "stringlit"
        if isinstance(n, NullLit):
            return "null"
        if isinstance(n, (NumberLit, BoolLit)):
            return "value"
        if isinstance(n, UnaryOp):
            k = kind_of(n.operand)
            if k in ("string", "stringlit"):
                raise PredicateParseError(
                    f"{'negation' if n.op == 'NEG' else 'NOT'} is "
                    "undefined for string operands"
                )
            return "value"
        if isinstance(n, IsNull):
            kind_of(n.operand)
            return "value"
        if isinstance(n, Between):
            check_cmp(n.operand, n.low)
            check_cmp(n.operand, n.high)
            return "value"
        if isinstance(n, CaseWhen):
            for cond, result in n.whens:
                if kind_of(cond) in ("string", "stringlit"):
                    raise PredicateParseError(
                        "a CASE condition must be boolean, not a bare "
                        "string operand"
                    )
                if kind_of(result) in ("string", "stringlit"):
                    raise PredicateParseError(
                        "string-valued CASE results are not supported"
                    )
            if n.else_ is not None and kind_of(n.else_) in (
                "string", "stringlit",
            ):
                raise PredicateParseError(
                    "string-valued CASE results are not supported"
                )
            return "value"
        if isinstance(n, InList):
            base = kind_of(n.operand)
            for item in n.items:
                if isinstance(item, NullLit):
                    continue
                item_kind = kind_of(item)
                if base == "string" and item_kind != "stringlit":
                    raise PredicateParseError(
                        "IN on a string column requires string literals"
                    )
                if base != "string" and item_kind == "stringlit":
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
            return "value"
        if isinstance(n, Like):
            if kind_of(n.operand) != "string":
                raise PredicateParseError("LIKE requires a string column")
            return "value"
        if isinstance(n, Cast):
            if n.type_name not in _CAST_TYPES:
                raise PredicateParseError(
                    f"CAST to {n.type_name} is not supported "
                    "(numeric targets only)"
                )
            k = kind_of(n.operand)
            if k == "stringlit":
                raise PredicateParseError(
                    "CAST of a string literal is constant"
                )
            if k == "timestamp":
                # raw epoch values are in the STORAGE unit (us/ns/...);
                # Spark's cast(timestamp as bigint) yields SECONDS —
                # returning unit-dependent numbers would be silently
                # wrong, so refuse (compare against date literals
                # instead, which convert through the column's unit)
                raise PredicateParseError(
                    "CAST of a timestamp column is not supported — "
                    "compare against 'YYYY-MM-DD' literals instead"
                )
            return "value"
        if isinstance(n, FuncCall):
            # the predicate evaluator supports only these functions;
            # aggregates (SUM/COUNT/...) belong to CustomSql expressions
            # and must fail HERE (planning time), not mid-trace where
            # they would poison every co-scheduled analyzer
            if n.name not in (
                "ABS", "LENGTH", "COALESCE", "CONCAT",
                "DATE_ADD", "DATE_SUB", "DATEDIFF",
            ) + _STRING_FNS:
                raise PredicateParseError(
                    f"unsupported function {n.name} in a predicate"
                )
            if n.name in ("DATE_ADD", "DATE_SUB"):
                if len(n.args) != 2:
                    raise PredicateParseError(
                        f"{n.name} takes (timestamp column, days)"
                    )
                if kind_of(n.args[0]) != "timestamp":
                    raise PredicateParseError(
                        f"{n.name} requires a timestamp/date column"
                    )
                _static_int(n.args[1], f"{n.name} day count")
                return "timestamp"
            if n.name == "DATEDIFF":
                if len(n.args) != 2:
                    raise PredicateParseError(
                        "DATEDIFF takes (end, start)"
                    )
                kinds_ = []
                for a in n.args:
                    k = kind_of(a)
                    if k == "stringlit":
                        assert isinstance(a, StringLit)
                        _validate_date_literal(a.value)
                    elif k != "timestamp":
                        raise PredicateParseError(
                            "DATEDIFF arguments must be timestamp "
                            "columns or date literals"
                        )
                    kinds_.append(k)
                if all(k == "stringlit" for k in kinds_):
                    raise PredicateParseError(
                        "DATEDIFF of two literals is constant"
                    )
                return "value"
            if n.name == "CONCAT":
                if not n.args:
                    raise PredicateParseError("CONCAT needs arguments")
                col_args = 0
                for a in n.args:
                    k = kind_of(a)
                    if k == "string":
                        col_args += 1
                    elif k != "stringlit":
                        raise PredicateParseError(
                            "CONCAT arguments must be strings"
                        )
                if col_args == 0:
                    raise PredicateParseError(
                        "CONCAT of only literals is constant"
                    )
                if col_args > 1:
                    raise PredicateParseError(
                        "CONCAT supports at most ONE column operand "
                        "(cross-dictionary concatenation is not "
                        "supported)"
                    )
                return "string"
            for a in n.args:
                if isinstance(a, StarLit):
                    raise PredicateParseError(
                        f"* is not a valid argument to {n.name}"
                    )
            if n.name in _STRING_FNS:
                # FULL static validation here: a raise later, inside
                # the shared fused-scan trace, would poison every
                # co-scheduled analyzer (this module's core invariant)
                if n.name in ("SUBSTR", "SUBSTRING"):
                    if len(n.args) not in (2, 3):
                        raise PredicateParseError(
                            f"{n.name} takes (string, pos[, length])"
                        )
                    _static_int(n.args[1], f"{n.name} position")
                    if len(n.args) == 3:
                        _static_int(n.args[2], f"{n.name} length")
                elif len(n.args) != 1:
                    raise PredicateParseError(
                        f"{n.name} takes exactly one argument"
                    )
                if kind_of(n.args[0]) != "string":
                    raise PredicateParseError(
                        f"{n.name} requires a string column operand"
                    )
                return "string"
            if n.name == "COALESCE":
                for a in n.args:
                    if kind_of(a) in ("string", "stringlit"):
                        raise PredicateParseError(
                            "COALESCE over string columns is not "
                            "supported (numeric/boolean arguments only)"
                        )
                return "value"
            if n.name == "LENGTH":
                for a in n.args:
                    kind_of(a)
                return "value"
            for a in n.args:
                kind_of(a)
            return "value"
        if isinstance(n, BinOp):
            if n.op in ("AND", "OR"):
                for side in (n.left, n.right):
                    if kind_of(side) in ("string", "stringlit"):
                        raise PredicateParseError(
                            "a bare string operand is not a boolean "
                            f"(in {n.op})"
                        )
                return "value"
            lk, rk = kind_of(n.left), kind_of(n.right)
            if n.op in _CMP:
                check_kinds(lk, rk, n.op)
                check_ts_literal(n.left, lk, n.right, rk)
                return "value"
            # arithmetic
            for k in (lk, rk):
                if k in ("string", "stringlit"):
                    raise PredicateParseError(
                        f"arithmetic {n.op!r} is undefined for string "
                        "operands"
                    )
            return "value"
        return "value"

    def check_kinds(lk: str, rk: str, op: str) -> None:
        stringish = ("string", "stringlit")
        if "null" in (lk, rk):
            return
        # timestamp vs string literal: the literal is a date — valid
        if {"timestamp", "stringlit"} == {lk, rk}:
            return
        if lk == "timestamp":
            lk = "value"
        if rk == "timestamp":
            rk = "value"
        if (lk in stringish) != (rk in stringish):
            raise PredicateParseError(
                "cannot compare a string operand with a non-string "
                "operand (dictionary codes are not values)"
            )
        if lk == "stringlit" and rk == "stringlit":
            raise PredicateParseError(
                f"comparison {op!r} of two string literals is constant"
            )

    def check_ts_literal(a: Node, ak: str, b: Node, bk: str) -> None:
        """A timestamp-vs-string-literal compare carries a STATIC date
        literal — validate it NOW (plan time), not mid-trace."""
        import datetime as _dt

        for node_, kind_, other in ((a, ak, bk), (b, bk, ak)):
            if kind_ == "stringlit" and other == "timestamp":
                assert isinstance(node_, StringLit)
                _validate_date_literal(node_.value)

    def check_cmp(a: Node, b: Node) -> None:
        check_kinds(kind_of(a), kind_of(b), "BETWEEN")
        check_ts_literal(a, kind_of(a), b, kind_of(b))

    return kind_of(node)


def _children_of(node: Node):
    """Every child Node, uniformly across node shapes (incl. CASE)."""
    for attr in ("operand", "left", "right", "low", "high", "else_"):
        child = getattr(node, attr, None)
        if isinstance(child, Node):
            yield child
    for attr in ("items", "args"):
        for child in getattr(node, attr, ()):
            if isinstance(child, Node):
                yield child
    for pair in getattr(node, "whens", ()):
        yield pair[0]
        yield pair[1]


def _length_columns_of(node: Node) -> set:
    """Columns appearing as LENGTH(col) — they need the 'lengths' repr."""
    out: set = set()
    if isinstance(node, FuncCall) and node.name == "LENGTH":
        for arg in node.args:
            if isinstance(arg, ColumnRef):
                out.add(arg.name)
    for child in _children_of(node):
        out |= _length_columns_of(child)
    return out


def _columns_of(node: Node) -> set:
    if isinstance(node, ColumnRef):
        return {node.name}
    out: set = set()
    for child in _children_of(node):
        out |= _columns_of(child)
    return out


def _as_bool(v: _Val) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if v.is_bool:
        return v.values.astype(bool), v.valid
    return v.values != 0, v.valid


_CMP = ("=", "!=", "<", "<=", ">", ">=")
_CMP_FNS = {
    "=": jnp.equal,
    "!=": jnp.not_equal,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
}


def _dict_lookup(dataset: Dataset, column: str, value: str) -> int:
    dictionary = dataset.dictionary(column)
    matches = np.nonzero(dictionary == value)[0]
    return int(matches[0]) if len(matches) else -2  # -2: matches nothing


def _string_eq_lut(ds: Dataset, base: "_Val", literal: str) -> jnp.ndarray:
    """Per-code bool LUT for ``view(dict[i]) == literal`` — required
    when a transform applies (several raw entries may map to the same
    transformed value, so a single-code lookup can't represent it)."""
    dictionary = ds.dictionary(base.codes_of)
    table = np.zeros(len(dictionary) + 1, dtype=bool)
    for i, s in enumerate(dictionary):
        if s is not None and base.view(str(s)) == literal:
            table[i] = True
    lut = jnp.asarray(table)
    idx = jnp.where(base.values < 0, len(dictionary), base.values)
    return lut[jnp.clip(idx, 0, len(dictionary))]


def _rank_table(
    views: "list[list[str]]", extra: "list[str]"
) -> "dict[str, int]":
    """Lexicographic rank of every distinct string across the given
    (already-transformed) dictionary views (+ literals): the shared
    value domain that makes codes from unrelated dictionaries — or
    transformed views of them — comparable."""
    values = set(extra)
    for view in views:
        values.update(v for v in view if v is not None)
    return {v: i for i, v in enumerate(sorted(values))}


def _dict_view(ds: Dataset, val: "_Val") -> "list[Optional[str]]":
    """The dictionary as the expression sees it: transform applied."""
    return [
        None if v is None else val.view(str(v))
        for v in ds.dictionary(val.codes_of)
    ]


def _ranks_for(
    view: "list[Optional[str]]", rank: "dict[str, int]"
) -> np.ndarray:
    """int32 LUT code -> shared rank; one trailing slot (-1) for null
    codes so a single clipped gather covers every code."""
    out = np.full(len(view) + 1, -1, dtype=np.int32)
    for i, v in enumerate(view):
        if v is not None:
            out[i] = rank[v]
    return out


def _gather_ranks(lut: np.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    table = jnp.asarray(lut)
    idx = jnp.where(codes < 0, table.shape[0] - 1, codes)
    return table[jnp.clip(idx, 0, table.shape[0] - 1)]


def _shared_rank_luts(dataset: Dataset, a: "_Val", b: "_Val"):
    va, vb = _dict_view(dataset, a), _dict_view(dataset, b)
    rank = _rank_table(
        [[x for x in va if x is not None], [x for x in vb if x is not None]],
        [],
    )
    return _ranks_for(va, rank), _ranks_for(vb, rank)


def _rank_lut_with_literal(dataset: Dataset, base: "_Val", literal: str):
    view = _dict_view(dataset, base)
    rank = _rank_table([[x for x in view if x is not None]], [literal])
    return _ranks_for(view, rank), rank[literal]


_STRING_FNS = ("TRIM", "LTRIM", "RTRIM", "UPPER", "LOWER", "SUBSTR",
               "SUBSTRING")
_CAST_TYPES = (
    "INT", "INTEGER", "BIGINT", "LONG", "SMALLINT", "TINYINT",
    "FLOAT", "DOUBLE", "REAL",
)
_INT_CASTS = ("INT", "INTEGER", "BIGINT", "LONG", "SMALLINT", "TINYINT")
# JVM d2i-style saturation bounds per integral target (f64 lane: the
# i64 bounds round to the nearest representable double)
_INT_CAST_BOUNDS = {
    "INT": (-2147483648.0, 2147483647.0),
    "INTEGER": (-2147483648.0, 2147483647.0),
    "BIGINT": (-9.223372036854776e18, 9.223372036854776e18),
    "LONG": (-9.223372036854776e18, 9.223372036854776e18),
    "SMALLINT": (-32768.0, 32767.0),
    "TINYINT": (-128.0, 127.0),
}


def _static_int(node: Node, what: str) -> int:
    """A SUBSTR position/length argument must be a static integer."""
    if isinstance(node, UnaryOp) and node.op == "NEG":
        return -_static_int(node.operand, what)
    if isinstance(node, NumberLit) and float(node.value).is_integer():
        return int(node.value)
    raise PredicateParseError(f"{what} must be an integer literal")


def _substr(s: str, pos: int, length: Optional[int]) -> str:
    """Spark substring semantics: 1-based; pos 0 behaves like 1;
    negative pos counts from the end; negative length -> empty."""
    if pos > 0:
        start = pos - 1
    elif pos < 0:
        start = max(len(s) + pos, 0)
    else:
        start = 0
    if length is None:
        return s[start:]
    if length <= 0:
        return ""
    return s[start:start + length]


def _eval_string_fn(
    node: "FuncCall", batch: Dict[str, jnp.ndarray], ds: Dataset
) -> "_Val":
    """TRIM/LTRIM/RTRIM/UPPER/LOWER/SUBSTR compose a host-side
    transform over the operand's dictionary view; codes/validity pass
    through untouched (the device never sees strings)."""
    if node.name in ("SUBSTR", "SUBSTRING"):
        if len(node.args) not in (2, 3):
            raise PredicateParseError(
                f"{node.name} takes (string, pos[, length])"
            )
        base = _eval(node.args[0], batch, ds)
        pos = _static_int(node.args[1], f"{node.name} position")
        length = (
            _static_int(node.args[2], f"{node.name} length")
            if len(node.args) == 3
            else None
        )
        inner = base.view

        def transform(s: str, _pos=pos, _len=length, _inner=inner):
            return _substr(_inner(s), _pos, _len)

    else:
        if len(node.args) != 1:
            raise PredicateParseError(
                f"{node.name} takes exactly one argument"
            )
        base = _eval(node.args[0], batch, ds)
        inner = base.view
        fn = {
            "TRIM": str.strip,
            "LTRIM": str.lstrip,
            "RTRIM": str.rstrip,
            "UPPER": str.upper,
            "LOWER": str.lower,
        }[node.name]

        def transform(s: str, _fn=fn, _inner=inner):
            return _fn(_inner(s))

    if base.codes_of is None:
        raise PredicateParseError(
            f"{node.name} requires a string column operand"
        )
    return _Val(
        base.values, base.valid, codes_of=base.codes_of,
        transform=transform,
    )


def _units_per_day(arrow_type) -> int:
    """How many of the column's int64 epoch units make one UTC day
    (mirrors the values-repr cast in data.table.convert_basic_repr)."""
    import pyarrow as pa

    if pa.types.is_date32(arrow_type):
        return 1
    if pa.types.is_date64(arrow_type):
        return 86_400_000
    unit = getattr(arrow_type, "unit", "us")
    return 86_400 * {
        "s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000
    }[unit]


def _epoch_days_of_literal(literal: str) -> int:
    import datetime as _dt

    d = _dt.datetime.fromisoformat(literal).date()
    return (d - _dt.date(1970, 1, 1)).days


def _date_literal_epoch(ds, column: str, literal: str) -> int:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' -> the column's int64 epoch
    value (same cast the values repr uses: pc.cast(col, int64) keeps
    the storage unit, so converting the LITERAL through the same arrow
    type makes the numeric compare exact)."""
    import datetime as _dt

    import pyarrow as pa
    import pyarrow.compute as pc

    try:
        dt = _dt.datetime.fromisoformat(literal)
    except ValueError as exc:
        raise PredicateParseError(
            f"{literal!r} is not a date/timestamp literal "
            "(YYYY-MM-DD[ HH:MM:SS])"
        ) from exc
    arrow_type = ds.arrow_type(column)
    value = dt.date() if pa.types.is_date(arrow_type) else dt
    arr = pa.array([value], type=arrow_type)
    if pa.types.is_date32(arrow_type):
        # Arrow has no date32->int64 kernel; hop through int32 — the
        # SAME two-step the values repr uses (convert_basic_repr), so
        # literal and column land in identical units (days)
        arr = pc.cast(arr, pa.int32())
    return int(pc.cast(arr, pa.int64())[0].as_py())


def _eval(node: Node, batch: Dict[str, jnp.ndarray], ds: Dataset) -> _Val:
    if isinstance(node, ColumnRef):
        kind = ds.schema.kind_of(node.name)
        mask = batch[f"{node.name}::mask"]
        if kind == Kind.STRING:
            return _Val(batch[f"{node.name}::codes"], mask, codes_of=node.name)
        vals = batch[f"{node.name}::values"]
        is_ts = kind == Kind.TIMESTAMP
        return _Val(
            vals,
            mask,
            is_bool=kind == Kind.BOOLEAN,
            ts_col=node.name if is_ts else None,
            ts_per_day=(
                _units_per_day(ds.arrow_type(node.name)) if is_ts else None
            ),
        )
    if isinstance(node, NumberLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True))
    if isinstance(node, BoolLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True), is_bool=True)
    if isinstance(node, NullLit):
        return _Val(jnp.asarray(0.0), jnp.asarray(False))
    if isinstance(node, StringLit):
        # bare string literal only makes sense inside comparisons, which
        # special-case it; standing alone it is an error
        raise PredicateParseError(
            f"string literal {node.value!r} outside comparison"
        )
    if isinstance(node, UnaryOp):
        if node.op == "NEG":
            v = _eval(node.operand, batch, ds)
            return _Val(-v.values, v.valid)
        truth, valid = _as_bool(_eval(node.operand, batch, ds))
        return _Val(~truth, valid, is_bool=True)
    if isinstance(node, IsNull):
        v = _eval(node.operand, batch, ds)
        res = v.valid if node.negate else ~v.valid
        return _Val(res, jnp.ones_like(res, dtype=bool), is_bool=True)
    if isinstance(node, Between):
        return _eval(
            BinOp(
                "AND",
                BinOp(">=", node.operand, node.low),
                BinOp("<=", node.operand, node.high),
            ),
            batch,
            ds,
        )
    if isinstance(node, Cast):
        v = _eval(node.operand, batch, ds)
        integral = node.type_name in _INT_CASTS
        if v.codes_of is not None:
            # string column: parse each dictionary entry ONCE
            # (Spark cast semantics: unparseable -> NULL). Validity
            # lives in its OWN table — overloading NaN as the invalid
            # sentinel would misreport an entry 'NaN' (which Spark
            # casts to the VALUE NaN) as NULL (r4 advisory).
            dictionary = ds.dictionary(v.codes_of)
            table = np.zeros(len(dictionary) + 1)
            ok = np.zeros(len(dictionary) + 1, dtype=bool)
            for i, s in enumerate(dictionary):
                if s is not None:
                    text = v.view(str(s)).strip()
                    if "_" in text:  # Python-only numeric syntax
                        continue  # ('1_0'); Spark casts it to NULL
                    try:
                        table[i] = float(text)
                        ok[i] = True
                    except ValueError:
                        pass
            lut = jnp.asarray(table)
            ok_lut = jnp.asarray(ok)
            idx = jnp.clip(
                jnp.where(v.values < 0, len(dictionary), v.values),
                0,
                len(dictionary),
            )
            vals = lut[idx]
            valid = v.valid & ok_lut[idx]
            vals = jnp.where(valid, vals, 0.0)
            if integral:
                # a string with no finite numeric value has no
                # integral parse -> NULL (Spark's string-to-int cast
                # rejects 'NaN'/'Infinity'; review finding on the r4
                # validity-table fix)
                finite = jnp.isfinite(vals)
                valid = valid & finite
                vals = jnp.trunc(jnp.where(finite, vals, 0.0))
            return _Val(vals, valid)
        vals = v.values.astype(jnp.float64)
        valid = v.valid
        if integral:
            # numeric source follows JVM double-to-int conversion like
            # non-ANSI Spark: truncate toward zero, SATURATE at the
            # target bounds, NaN -> 0 (NOT NULL — review finding)
            lo, hi = _INT_CAST_BOUNDS[node.type_name]
            vals = jnp.clip(jnp.trunc(vals), lo, hi)
            vals = jnp.where(jnp.isnan(vals), 0.0, vals)
        return _Val(vals, valid)
    if isinstance(node, CaseWhen):
        # SQL: first branch whose condition is TRUE wins (NULL
        # conditions skip); no match and no ELSE -> NULL. Folded in
        # reverse so earlier branches override later ones.
        if node.else_ is not None:
            acc = _eval(node.else_, batch, ds)
        else:
            acc = _Val(jnp.asarray(0.0), jnp.asarray(False))
        if acc.codes_of is not None:
            raise PredicateParseError(
                "string-valued CASE results are not supported"
            )
        # branch values coerce to f64 (SQL promotes mixed numeric/bool
        # CASE branches); truth of the result is still `!= 0`
        vals = jnp.asarray(acc.values, dtype=jnp.float64)
        valid = acc.valid
        for cond, result in reversed(node.whens):
            ct, cv = _as_bool(_eval(cond, batch, ds))
            hit = ct & cv
            r = _eval(result, batch, ds)
            if r.codes_of is not None:
                raise PredicateParseError(
                    "string-valued CASE results are not supported"
                )
            vals = jnp.where(
                hit, jnp.asarray(r.values, dtype=jnp.float64), vals
            )
            valid = jnp.where(hit, r.valid, valid)
        return _Val(vals, valid)
    if isinstance(node, InList):
        base = _eval(node.operand, batch, ds)
        truth = jnp.zeros_like(base.values, dtype=bool)
        has_null_item = False
        for item in node.items:
            if isinstance(item, NullLit):
                # SQL: x IN (..., NULL) is TRUE on a match, else NULL
                has_null_item = True
            elif isinstance(item, StringLit):
                if base.codes_of is None:
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
                if base.transform is not None:
                    truth = truth | _string_eq_lut(ds, base, item.value)
                else:
                    code = _dict_lookup(ds, base.codes_of, item.value)
                    truth = truth | (base.values == code)
            else:
                rhs = _eval(item, batch, ds)
                truth = truth | ((base.values == rhs.values) & rhs.valid)
        valid = base.valid
        if has_null_item:
            valid = valid & truth  # non-matches become NULL
        if node.negate:
            truth = ~truth
        return _Val(truth, valid, is_bool=True)
    if isinstance(node, Like):
        base = _eval(node.operand, batch, ds)
        if base.codes_of is None:
            raise PredicateParseError("LIKE requires a string column")
        dictionary = ds.dictionary(base.codes_of)
        pattern = (
            node.pattern if node.regex else _sql_like_to_regex(node.pattern)
        )
        prog = re.compile(pattern)
        table = np.zeros(len(dictionary) + 1, dtype=bool)
        for i, s in enumerate(dictionary):
            if s is not None and prog.search(base.view(str(s))):
                table[i] = True
        lut = jnp.asarray(table)
        truth = lut[jnp.clip(base.values, -1, len(dictionary) - 1)]
        truth = jnp.where(base.values < 0, False, truth)
        if node.negate:
            truth = ~truth
        return _Val(truth, base.valid, is_bool=True)
    if isinstance(node, FuncCall):
        if node.name == "ABS" and len(node.args) == 1:
            v = _eval(node.args[0], batch, ds)
            return _Val(jnp.abs(v.values), v.valid)
        if node.name == "COALESCE":
            if not node.args:
                raise PredicateParseError("COALESCE needs arguments")
            parts = [_eval(a, batch, ds) for a in node.args]
            if any(p.codes_of is not None for p in parts):
                raise PredicateParseError(
                    "COALESCE over string columns is not supported "
                    "(numeric/boolean arguments only)"
                )
            vals = parts[0].values
            valid = parts[0].valid
            for p in parts[1:]:
                vals = jnp.where(valid, vals, p.values)
                valid = valid | p.valid
            return _Val(
                vals, valid, is_bool=all(p.is_bool for p in parts)
            )
        if node.name == "LENGTH" and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ColumnRef):
                mask = batch[f"{arg.name}::mask"]
                return _Val(batch[f"{arg.name}::lengths"], mask)
            # LENGTH over a transformed string expression: per-code
            # i32 LUT of len(view(dict[i])), gathered by code
            v = _eval(arg, batch, ds)
            if v.codes_of is None:
                raise PredicateParseError(
                    "LENGTH expects a string column or string function"
                )
            dictionary = ds.dictionary(v.codes_of)
            table = np.zeros(len(dictionary) + 1, dtype=np.int32)
            for i, s in enumerate(dictionary):
                if s is not None:
                    table[i] = len(v.view(str(s)))
            lut = jnp.asarray(table)
            idx = jnp.where(v.values < 0, len(dictionary), v.values)
            return _Val(
                lut[jnp.clip(idx, 0, len(dictionary))], v.valid
            )
        if node.name in ("DATE_ADD", "DATE_SUB"):
            v = _eval(node.args[0], batch, ds)
            if v.ts_per_day is None:
                raise PredicateParseError(
                    f"{node.name} requires a timestamp/date column"
                )
            n_days = _static_int(node.args[1], f"{node.name} day count")
            if node.name == "DATE_SUB":
                n_days = -n_days
            # Spark's date_add casts to DATE first: the result is
            # DAY-valued (time-of-day truncates), so equality against
            # date literals behaves like Spark's
            days = jnp.floor_divide(
                v.values.astype(jnp.int64), jnp.int64(v.ts_per_day)
            )
            return _Val(
                days + jnp.int64(n_days), v.valid, ts_per_day=1
            )
        if node.name == "DATEDIFF":
            def days_of(arg):
                if isinstance(arg, StringLit):
                    return (
                        jnp.int64(_epoch_days_of_literal(arg.value)),
                        jnp.asarray(True),
                    )
                v = _eval(arg, batch, ds)
                if v.ts_per_day is None:
                    raise PredicateParseError(
                        "DATEDIFF arguments must be timestamp columns "
                        "or date literals"
                    )
                return (
                    jnp.floor_divide(
                        v.values.astype(jnp.int64),
                        jnp.int64(v.ts_per_day),
                    ),
                    v.valid,
                )

            end_days, end_valid = days_of(node.args[0])
            start_days, start_valid = days_of(node.args[1])
            return _Val(end_days - start_days, end_valid & start_valid)
        if node.name == "CONCAT":
            # at most ONE column operand (checked at plan time):
            # literals fold into the transform around it
            col_val = None
            parts = []
            for a in node.args:
                if isinstance(a, StringLit):
                    parts.append(a.value)
                else:
                    v = _eval(a, batch, ds)
                    if v.codes_of is None:
                        raise PredicateParseError(
                            "CONCAT arguments must be strings"
                        )
                    if col_val is not None:
                        raise PredicateParseError(
                            "CONCAT supports at most ONE column operand"
                        )
                    col_val = v
                    parts.append(None)  # the column slot
            if col_val is None:
                raise PredicateParseError(
                    "CONCAT of only literals is constant"
                )
            inner = col_val.view

            def transform(s, _parts=tuple(parts), _inner=inner):
                return "".join(
                    _inner(s) if p is None else p for p in _parts
                )

            return _Val(
                col_val.values,
                col_val.valid,
                codes_of=col_val.codes_of,
                transform=transform,
            )
        if node.name in _STRING_FNS:
            return _eval_string_fn(node, batch, ds)
        raise PredicateParseError(f"unsupported function {node.name}")
    if isinstance(node, BinOp):
        if node.op in ("AND", "OR"):
            lt, lv = _as_bool(_eval(node.left, batch, ds))
            rt, rv = _as_bool(_eval(node.right, batch, ds))
            if node.op == "AND":
                truth = lt & rt
                # SQL 3VL: FALSE AND NULL = FALSE (valid)
                valid = (lv & rv) | (lv & ~lt) | (rv & ~rt)
            else:
                truth = lt | rt
                # TRUE OR NULL = TRUE (valid)
                valid = (lv & rv) | (lv & lt) | (rv & rt)
            return _Val(truth, valid, is_bool=True)
        # comparisons involving string literals: =/!= compare raw codes
        # (one O(n) dictionary lookup, scalar compare); orderings need
        # lexicographic ranks — codes are in order of appearance
        if node.op in _CMP and (
            isinstance(node.left, StringLit) or isinstance(node.right, StringLit)
        ):
            lit_on_right = isinstance(node.right, StringLit)
            col_node, lit = (
                (node.left, node.right)
                if lit_on_right
                else (node.right, node.left)
            )
            base = _eval(col_node, batch, ds)
            if base.ts_per_day is not None:
                # timestamp/date lane vs date literal: the literal
                # converts to the lane's epoch unit at trace time (via
                # the exact Arrow cast for raw columns; as UTC days
                # for day-valued DATE_ADD results); the device compare
                # stays numeric
                if base.ts_col is not None:
                    epoch = _date_literal_epoch(
                        ds, base.ts_col, lit.value
                    )
                else:
                    epoch = _epoch_days_of_literal(lit.value)
                lv, rv = (
                    (base.values, epoch)
                    if lit_on_right
                    else (epoch, base.values)
                )
                return _Val(
                    _CMP_FNS[node.op](lv, rv), base.valid, is_bool=True
                )
            if base.codes_of is None:
                raise PredicateParseError(
                    "string comparison requires a string column"
                )
            if node.op in ("=", "!="):
                if base.transform is not None:
                    truth = _string_eq_lut(ds, base, lit.value)
                else:
                    code = _dict_lookup(ds, base.codes_of, lit.value)
                    truth = base.values == code
                if node.op == "!=":
                    truth = ~truth
                return _Val(truth, base.valid, is_bool=True)
            ranks, lit_rank = _rank_lut_with_literal(
                ds, base, lit.value
            )
            col_ranks = _gather_ranks(ranks, base.values)
            lv, rv = (
                (col_ranks, lit_rank) if lit_on_right else (lit_rank, col_ranks)
            )
            return _Val(_CMP_FNS[node.op](lv, rv), base.valid, is_bool=True)
        lhs = _eval(node.left, batch, ds)
        rhs = _eval(node.right, batch, ds)
        valid = lhs.valid & rhs.valid
        lv, rv = lhs.values, rhs.values
        if (
            node.op in _CMP
            and lhs.ts_per_day is not None
            and rhs.ts_per_day is not None
            and lhs.ts_per_day != rhs.ts_per_day
        ):
            # mixed-unit timestamp lanes (timestamp[us] vs date32, or
            # a day-valued DATE_ADD vs a raw column): scale the coarser
            # side up to the finer unit so epochs compare as instants
            # (comparing raw epochs across units would be silently
            # wrong — r4 review finding)
            if lhs.ts_per_day < rhs.ts_per_day:
                lv = lv.astype(jnp.int64) * jnp.int64(
                    rhs.ts_per_day // lhs.ts_per_day
                )
            else:
                rv = rv.astype(jnp.int64) * jnp.int64(
                    lhs.ts_per_day // rhs.ts_per_day
                )
        if node.op in _CMP:
            if lhs.codes_of is not None and rhs.codes_of is not None:
                # two string columns: dictionary codes come from
                # UNRELATED dictionaries (and even one dictionary is in
                # order of appearance, not sorted) — remap both sides to
                # ranks in a shared sorted value domain so =/!= and
                # lexicographic ordering are exact
                lut_l, lut_r = _shared_rank_luts(ds, lhs, rhs)
                lv = _gather_ranks(lut_l, lv)
                rv = _gather_ranks(lut_r, rv)
            elif (lhs.codes_of is None) != (rhs.codes_of is None):
                raise PredicateParseError(
                    "cannot compare a string column with a non-string "
                    "operand (dictionary codes are not values)"
                )
            return _Val(_CMP_FNS[node.op](lv, rv), valid, is_bool=True)
        if lhs.codes_of is not None or rhs.codes_of is not None:
            raise PredicateParseError(
                f"arithmetic {node.op!r} is undefined for string columns"
            )
        if node.op == "+":
            return _Val(lv + rv, valid)
        if node.op == "-":
            return _Val(lv - rv, valid)
        if node.op == "*":
            return _Val(lv * rv, valid)
        if node.op == "/":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv / safe, valid & denom_ok)
        if node.op == "%":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv % safe, valid & denom_ok)
    raise PredicateParseError(f"cannot evaluate node {node!r}")
