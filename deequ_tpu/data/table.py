"""Columnar dataset: Arrow ingest and device-batch materialization.

This is deequ_tpu's L0/L1 replacement for Spark DataFrames (SURVEY.md §1,
§7 stage 0). A :class:`Dataset` wraps a ``pyarrow.Table`` and materializes
*device representations* of columns on demand:

- ``values``   — numeric payload (nulls zero-filled; see mask); int64
                 columns narrow to i32 when every value fits (wire
                 bytes are the bottleneck)
- ``mask``     — validity bitmap as bool (True = non-null), AND row mask
- ``codes``    — dictionary codes for string/categorical columns —
                 i8/i16/i32 depending on dictionary size (widen before
                 any joint-code arithmetic!) — with the dictionary kept
                 host-side (strings never reach the TPU — SURVEY.md §7
                 hard part #3)
- ``lengths``  — utf8 lengths for string columns (MinLength/MaxLength)

Batches are fixed-size and zero-padded (padding rows carry
``__row_mask__ == False``) so that every batch has the same static shape
and the fused analyzer scan compiles exactly once.
"""

from __future__ import annotations

import enum
import functools
import hashlib
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

ROW_MASK = "__row_mask__"

# Host-only dictionary-delta payloads riding a streamed batch dict
# (data/parquet.py produces them, the engine's streaming loop pops them
# before transfer and applies them to LUT-carrying op states): key
# ``DICT_DELTA_PREFIX + column`` -> {"start": int, "values": ndarray}.
# Never part of the wire layout, never device_put.
DICT_DELTA_PREFIX = "__dict_delta__:"

# -- host->device transfer accounting (monotonic; bench snapshots it) ----
# The tally lives on the telemetry registry now (counter
# "transfer.bytes" — always on, docs/OBSERVABILITY.md); these module
# functions remain as the stable accessors. Looked up per call, not
# cached: registry.reset() in tests would detach a cached instrument,
# and the lookup is per-BATCH, not per-row.
def _transfer_counter():
    from deequ_tpu.telemetry import get_telemetry

    return get_telemetry().counter("transfer.bytes")


def add_transfer_bytes(n: int) -> None:
    _transfer_counter().inc(int(n))


def transfer_bytes() -> int:
    """Total bytes shipped host->device by the data layer so far.
    Monotonic; callers snapshot around a run to decompose wall time into
    link vs compute (VERDICT.md r2 weak #6)."""
    return _transfer_counter().value


@functools.lru_cache(maxsize=None)
def _chunk_row_mask_fn(chunk_nb: int, batch_size: int):
    """Jitted builder of a (chunk_nb, batch_size) bool mask of in-bounds
    rows for the chunk starting at global row ``start`` — built ON
    device (iota fused into the comparison; no wire transfer). ``start``
    and ``n`` are runtime scalars so one compile serves every chunk."""
    import jax
    import jax.numpy as jnp

    def build(start, n):
        idx = jax.lax.broadcasted_iota(jnp.int64, (chunk_nb, batch_size), 0)
        off = jax.lax.broadcasted_iota(jnp.int64, (chunk_nb, batch_size), 1)
        return start + idx * batch_size + off < n

    # lint-ok: wire-discipline: resident-path device helper — the row
    # mask is BUILT on device (no wire transfer), not placed from host
    return jax.jit(build)


def _unpack_mask_bits(packed, batch_size: int):
    """Device: (chunk_nb, ceil(B/8)) uint8 little-endian packed bits ->
    (chunk_nb, B) bool. Validity masks cross the wire at 1 BIT/row
    (np.packbits host-side); this is the device-side expansion, fused by
    XLA into the consuming reductions' pass."""
    import jax.numpy as jnp

    bits = (packed[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    return bits.reshape(packed.shape[0], -1)[:, :batch_size].astype(bool)


@functools.lru_cache(maxsize=None)
def _mask_unpack_fn(batch_size: int):
    import jax

    # lint-ok: wire-discipline: the device-side half of the 1-bit/row
    # mask wire itself; the engine composes it into the fused unpack
    return jax.jit(
        functools.partial(_unpack_mask_bits, batch_size=batch_size)
    )


@functools.lru_cache(maxsize=None)
def _lengths_gather_fn():
    """Device: utf8 lengths derived from dictionary codes via LUT gather
    — string columns whose codes already ship (DataType/Histogram/HLL)
    get MinLength/MaxLength inputs for FREE instead of 4 more bytes/row
    over the wire. ``lut[0]`` is the null slot (length 0); codes are -1
    for null, so gather at code+1."""
    import jax
    import jax.numpy as jnp

    def gather(codes, lut):
        idx = codes.astype(jnp.int32) + 1
        return jnp.take(lut, jnp.clip(idx, 0, lut.shape[0] - 1), axis=0)

    # lint-ok: wire-discipline: wire-FREE lengths — the LUT gather
    # replaces a 4-bytes/row transfer, it does not add one
    return jax.jit(gather)


def narrow_codes(codes: np.ndarray, dict_size: int) -> np.ndarray:
    """Wire narrowing for dictionary codes: small dictionaries ship i8
    or i16 instead of i32 (4x/2x fewer bytes over the bottleneck
    host->device link). Bounds leave headroom for the +1 null-slot
    shift in the grouping joint-code math; -1 (null) fits every width."""
    if dict_size < 127:
        return codes.astype(np.int8)
    if dict_size < 32767:
        return codes.astype(np.int16)
    return codes


def dictionary_to_numpy(dictionary: pa.Array) -> np.ndarray:
    """Dictionary values as numpy: object arrays for strings, NATIVE
    dtype otherwise — a to_pylist object array costs seconds at 10M
    distinct values. One definition for the in-memory and parquet paths."""
    if pa.types.is_string(dictionary.type) or pa.types.is_large_string(
        dictionary.type
    ):
        return np.asarray(dictionary.to_pylist(), dtype=object)
    return dictionary.to_numpy(zero_copy_only=False)


def dictionary_utf8_lengths(dictionary: pa.Array) -> np.ndarray:
    """utf8 lengths of dictionary entries (null -> 0), i32 — computed by
    Arrow's C++ kernel once per DISTINCT value, not per row."""
    lengths = pc.fill_null(
        pc.utf8_length(dictionary), pa.scalar(0, pa.int32())
    )
    if isinstance(lengths, pa.ChunkedArray):
        lengths = lengths.combine_chunks()
    return np.ascontiguousarray(
        lengths.to_numpy(zero_copy_only=False).astype(np.int32)
    )


def convert_basic_repr(col, kind: "Kind", repr_name: str) -> np.ndarray:
    """The ONE host->device conversion rule set for mask/values/lengths
    (codes need a dictionary and stay with their owner). Shared by the
    in-memory and parquet paths so fill/widening semantics cannot drift."""
    if repr_name == "mask":
        if col.null_count == 0:
            out = np.ones(len(col), dtype=bool)
        else:
            is_null = col.is_null()
            if isinstance(is_null, pa.ChunkedArray):
                is_null = is_null.combine_chunks()
            out = ~is_null.to_numpy(zero_copy_only=False)
        return np.ascontiguousarray(out.astype(bool))
    if repr_name == "values":
        if kind == Kind.STRING:
            raise TypeError(
                "string columns have no 'values' repr; request 'codes' "
                "or 'lengths' instead"
            )
        filled = col
        if kind == Kind.TIMESTAMP:
            if pa.types.is_date32(col.type):
                # Arrow has no chunked date32->int64 kernel; hop
                # through int32 (days since epoch, exact)
                filled = pc.cast(pc.cast(col, pa.int32()), pa.int64())
            else:
                filled = pc.cast(col, pa.int64())
            if col.null_count:
                filled = pc.fill_null(filled, pa.scalar(0, pa.int64()))
        elif col.null_count:
            zero = (
                pa.scalar(False)
                if kind == Kind.BOOLEAN
                else pa.scalar(0, type=col.type)
            )
            filled = pc.fill_null(col, zero)
        if isinstance(filled, pa.ChunkedArray):
            filled = filled.combine_chunks()
        out = filled.to_numpy(zero_copy_only=False)
        if kind == Kind.BOOLEAN:
            out = out.astype(np.int32)
        elif out.dtype == np.float16:
            out = out.astype(np.float32)
        elif out.dtype.kind not in "iuf":
            out = out.astype(np.float64)
        return np.ascontiguousarray(out)
    if repr_name == "lengths":
        lengths = pc.fill_null(pc.utf8_length(col), pa.scalar(0, pa.int32()))
        if isinstance(lengths, pa.ChunkedArray):
            lengths = lengths.combine_chunks()
        return np.ascontiguousarray(
            lengths.to_numpy(zero_copy_only=False).astype(np.int32)
        )
    if repr_name == "u64bits":
        return f64_canonical_u64_bits(convert_basic_repr(col, kind, "values"))
    raise ValueError(f"unknown column repr: {repr_name!r}")


def f64_canonical_u64_bits(values: np.ndarray) -> np.ndarray:
    """HOST twin of the f64 spill-key canonicalization in
    analyzers/spill.py's ``_chunk_key_fn``, for backends whose X64
    rewriter cannot lower the f64->u64 bitcast on device (TPU):
    canonical NaN bits, -0.0 remapped to 0 — bit-identical to the CPU
    device path's keys. Backs the "u64bits" column repr, so the packed
    bits ride the normal column pipeline (one pass over the source)
    instead of forcing a separate host re-read per spill plan."""
    bits = (
        np.ascontiguousarray(values, dtype=np.float64)
        .view(np.uint64)
        .copy()
    )
    x = np.asarray(values, dtype=np.float64)
    bits[np.isnan(x)] = np.uint64(0x7FF8000000000000)
    bits[bits == np.uint64(0x8000000000000000)] = np.uint64(0)
    return bits


def narrow_int64_values(out: np.ndarray) -> np.ndarray:
    """Wire narrowing: host->device bandwidth is the bottleneck; when
    every value of an int64 column fits i32, ship half the bytes. Safe:
    every consumer canonicalizes integrals (HLL hashes via int64,
    sums/min/max widen to f64), so i32 and i64 storage of equal values
    produce identical metrics and merge compatibly across datasets.
    MUST be decided once per column (callers), never per batch — mixed
    batch dtypes would force a recompile per dtype combination."""
    if out.dtype == np.int64 and len(out):
        lo, hi = out.min(), out.max()
        if lo >= -(2**31) and hi < 2**31:
            return out.astype(np.int32)
    return out


class Kind(enum.Enum):
    """Logical column kinds (maps Arrow types to analyzer preconditions)."""

    INTEGRAL = "Integral"
    FRACTIONAL = "Fractional"
    BOOLEAN = "Boolean"
    STRING = "String"
    TIMESTAMP = "Timestamp"
    UNKNOWN = "Unknown"

    @property
    def is_numeric(self) -> bool:
        return self in (Kind.INTEGRAL, Kind.FRACTIONAL, Kind.BOOLEAN)


def normalize_float_grouping_keys(arr):
    """Spark grouping-key normalization for float columns, shared by
    the dictionary/codes path (Dataset._materialize_codes) and the
    Arrow group_by fallback (analyzers.grouping._normalize_float_keys):

    - pre-encoded float dictionaries are flattened first (the
      dictionary itself may hold -0.0 AND 0.0, or several NaN
      payloads, as distinct entries);
    - every NaN payload maps to the one canonical NaN — Arrow's
      group_by/dictionary_encode treat DIFFERENT NaN bit patterns as
      distinct keys (verified empirically), while Spark and the device
      spill kernel (spill._chunk_key_fn) group all NaNs together;
    - -0.0 maps to 0.0 via +0.0 (identity for every other value).

    Non-float arrays pass through untouched. tests/goldens neg_zero /
    nan fixtures pin the behavior."""
    if pa.types.is_dictionary(arr.type) and pa.types.is_floating(
        arr.type.value_type
    ):
        arr = pc.cast(arr, arr.type.value_type)
    if not pa.types.is_floating(arr.type):
        return arr
    return pc.if_else(
        pc.is_nan(arr),
        pa.scalar(float("nan"), arr.type),
        pc.add(arr, pa.scalar(0.0, arr.type)),
    )


def _kind_of(arrow_type: pa.DataType) -> Kind:
    if pa.types.is_boolean(arrow_type):
        return Kind.BOOLEAN
    if pa.types.is_integer(arrow_type):
        return Kind.INTEGRAL
    if pa.types.is_floating(arrow_type) or pa.types.is_decimal(arrow_type):
        return Kind.FRACTIONAL
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return Kind.STRING
    if pa.types.is_dictionary(arrow_type):
        return _kind_of(arrow_type.value_type)
    if pa.types.is_timestamp(arrow_type) or pa.types.is_date(arrow_type):
        return Kind.TIMESTAMP
    return Kind.UNKNOWN


@dataclass(frozen=True)
class Field:
    name: str
    kind: Kind


@dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def has_column(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def kind_of(self, name: str) -> Kind:
        for f in self.fields:
            if f.name == name:
                return f.kind
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)


@dataclass(frozen=True)
class ColumnRequest:
    """A device representation request: (column, repr)."""

    column: str
    # "values" | "mask" | "codes" | "lengths" | "u64bits" (host-packed
    # canonical f64 key bits for the one-pass spill collector)
    repr: str

    @property
    def key(self) -> str:
        return f"{self.column}::{self.repr}"


class Dataset:
    """In-memory columnar dataset over a ``pyarrow.Table``.

    Construction helpers accept Arrow tables, pandas DataFrames, or plain
    dicts of Python/numpy sequences. All device materializations are cached
    per (column, repr) as contiguous numpy arrays; batches are views plus a
    single zero-pad for the tail.
    """

    def __init__(self, table: pa.Table):
        self._table = table.combine_chunks()
        self._schema = Schema(
            tuple(
                Field(name, _kind_of(typ))
                for name, typ in zip(table.schema.names, table.schema.types)
            )
        )
        self._materialized: Dict[str, np.ndarray] = {}
        self._dictionaries: Dict[str, np.ndarray] = {}
        self._dict_lengths: Dict[str, np.ndarray] = {}
        # device-resident stacked batches, keyed (repr key, batch, sharding)
        self._device_cache: Dict = {}
        self._cache_key = id(self)
        weakref.finalize(self, Dataset._drop_cache_key, self._cache_key)

    # device-cache accounting is GLOBAL across Datasets (one chip, one
    # HBM): LRU registry of datasets holding device-resident columns
    _cache_registry: "OrderedDict[int, weakref.ref]" = OrderedDict()
    _cache_bytes_by_key: Dict[int, int] = {}

    @staticmethod
    def _drop_cache_key(key: int) -> None:
        Dataset._cache_registry.pop(key, None)
        Dataset._cache_bytes_by_key.pop(key, None)

    @staticmethod
    def global_device_cache_bytes() -> int:
        return sum(Dataset._cache_bytes_by_key.values())

    @property
    def _device_cache_bytes(self) -> int:
        return Dataset._cache_bytes_by_key.get(self._cache_key, 0)

    def _add_cache_bytes(self, nbytes: int) -> None:
        Dataset._cache_bytes_by_key[self._cache_key] = (
            self._device_cache_bytes + nbytes
        )

    def _touch_cache_registry(self) -> None:
        Dataset._cache_registry.pop(self._cache_key, None)
        Dataset._cache_registry[self._cache_key] = weakref.ref(self)

    # -- construction ---------------------------------------------------

    @staticmethod
    def from_arrow(table: pa.Table) -> "Dataset":
        return Dataset(table)

    @staticmethod
    def from_pandas(df) -> "Dataset":
        return Dataset(pa.Table.from_pandas(df, preserve_index=False))

    @staticmethod
    def from_pydict(data: Dict[str, Sequence]) -> "Dataset":
        return Dataset(pa.table(data))

    @staticmethod
    def from_parquet(source, read_batch_rows: int = 1 << 20) -> "Dataset":
        """Streaming parquet-backed dataset: batches are read and
        converted on the fly; whole columns are never materialized on
        the host unless the resident device cache opts in (see
        deequ_tpu.data.parquet)."""
        from deequ_tpu.data.parquet import ParquetDataset

        return ParquetDataset(source, read_batch_rows)

    # -- metadata -------------------------------------------------------

    @property
    def table(self) -> pa.Table:
        return self._table

    @property
    def num_rows(self) -> int:
        return self._table.num_rows

    @property
    def num_columns(self) -> int:
        return self._table.num_columns

    @property
    def schema(self) -> Schema:
        return self._schema

    def filter_rows(self, mask: np.ndarray) -> "Dataset":
        """Row subset (host-side); used by train/test splits and schema
        validation, not by the metric engine."""
        return Dataset(self._table.filter(pa.array(mask)))

    def select(self, columns: Sequence[str]) -> "Dataset":
        return Dataset(self._table.select(list(columns)))

    def record_batches(
        self, columns: Sequence[str], batch_rows: int = 1 << 20
    ) -> "Iterator[pa.RecordBatch]":
        """Column-pruned record batches (streamed from storage by
        parquet-backed datasets; zero-copy slices here)."""
        return iter(
            self._table.select(list(columns)).to_batches(batch_rows)
        )

    # -- dictionaries ---------------------------------------------------

    def dictionary(self, column: str) -> np.ndarray:
        """Host-side dictionary (unique values) for a column; codes index
        into this. Built once per column via Arrow's C++ kernels."""
        if column not in self._dictionaries:
            self._materialize_codes(column)
        return self._dictionaries[column]

    def _materialize_codes(self, column: str) -> None:
        arr = normalize_float_grouping_keys(self._table.column(column))
        if pa.types.is_dictionary(arr.type):
            dict_arr = arr.combine_chunks()
        else:
            dict_arr = pc.dictionary_encode(arr).combine_chunks()
        if isinstance(dict_arr, pa.ChunkedArray):
            dict_arr = dict_arr.combine_chunks()
        indices = dict_arr.indices
        codes = (
            pc.fill_null(indices, pa.scalar(-1, indices.type))
            .to_numpy(zero_copy_only=False)
            .astype(np.int32)
        )
        codes = narrow_codes(codes, len(dict_arr.dictionary))
        self._materialized[f"{column}::codes"] = np.ascontiguousarray(codes)
        self._dictionaries[column] = dictionary_to_numpy(dict_arr.dictionary)
        if self._schema.kind_of(column) == Kind.STRING:
            self._dict_lengths[column] = dictionary_utf8_lengths(
                dict_arr.dictionary
            )

    def dict_lengths(self, column: str) -> Optional[np.ndarray]:
        """Per-dictionary-entry utf8 lengths (i32) for a string column,
        or None when codes haven't been materialized. Used to derive
        the 'lengths' device repr from codes on device (see
        _lengths_gather_fn) instead of shipping 4 bytes/row."""
        if column not in self._dict_lengths and column in self._dictionaries:
            self._dict_lengths[column] = dictionary_utf8_lengths(
                pa.array(list(self._dictionaries[column]), pa.string())
            )
        return self._dict_lengths.get(column)

    # -- device materialization ----------------------------------------

    def materialize(self, req: ColumnRequest) -> np.ndarray:
        key = req.key
        if key in self._materialized:
            return self._materialized[key]
        if req.repr == "codes":
            self._materialize_codes(req.column)
            return self._materialized[key]
        col = self._table.column(req.column)
        kind = self._schema.kind_of(req.column)
        out = convert_basic_repr(col, kind, req.repr)
        if req.repr == "values" and kind == Kind.INTEGRAL:
            out = narrow_int64_values(out)  # whole column: one decision
        self._materialized[key] = out
        return out

    def request_dtype(self, req: ColumnRequest) -> np.dtype:
        """Dtype a device batch of this request will have (used by the
        vectorizing planner to group stackable columns). In-memory
        datasets answer from the (cached) materialization; streaming
        sources override with their pre-decided per-column dtypes."""
        if req.repr == "mask":
            return np.dtype(bool)
        if req.repr == "u64bits":
            return np.dtype(np.uint64)
        return np.dtype(self.materialize(req).dtype)

    # -- batching -------------------------------------------------------

    def device_batches(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: Optional[int] = None,
        start_batch: int = 0,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield fixed-size batches (host numpy; the engine device_puts).

        Every batch has identical shapes: the tail batch is zero-padded
        and padding rows have ``__row_mask__ == False``; per-column masks
        are pre-ANDed with the row mask so updates need a single mask.

        ``start_batch`` skips the first N batches — the engine's
        resilience layer restarts the stream from a failing batch
        (retry) or a checkpoint cursor (resume); batch boundaries are
        identical for every start, so batch ``i`` of a restarted stream
        is bit-identical to batch ``i`` of a full one.
        """
        n = self.num_rows
        if batch_size is None:
            batch_size = n if n > 0 else 1
        batch_size = max(1, batch_size)
        keys = self._dedup_requests(requests)
        full: Dict[str, np.ndarray] = {
            k: self.materialize(r) for k, r in keys.items()
        }
        if n == 0:
            if start_batch > 0:
                return
            batch = {
                k: np.zeros((batch_size,), dtype=v.dtype)
                for k, v in full.items()
            }
            batch[ROW_MASK] = np.zeros((batch_size,), dtype=bool)
            yield batch
            return
        for start in range(start_batch * batch_size, n, batch_size):
            stop = min(start + batch_size, n)
            width = stop - start
            pad = batch_size - width
            batch = {}
            for k, v in full.items():
                sl = v[start:stop]
                if pad:
                    sl = np.concatenate(
                        [sl, np.zeros((pad,), dtype=v.dtype)]
                    )
                batch[k] = sl
            row_mask = np.ones((batch_size,), dtype=bool)
            if pad:
                row_mask[width:] = False
            batch[ROW_MASK] = row_mask
            if pad:
                for k in list(batch.keys()):
                    if k.endswith("::mask"):
                        batch[k] = batch[k] & row_mask
            yield batch

    # -- device-resident batching (the TPU fast path) -------------------

    def _is_all_valid(self, column: str) -> bool:
        return self._table.column(column).null_count == 0

    @staticmethod
    def _dedup_requests(
        requests: Sequence[ColumnRequest],
    ) -> Dict[str, ColumnRequest]:
        """Dedup requests and add a validity-mask request per column —
        the one canonical definition the byte estimate, the resident
        path, and the streaming path all share."""
        keys: Dict[str, ColumnRequest] = {}
        for r in requests:
            keys.setdefault(r.key, r)
            mask_req = ColumnRequest(r.column, "mask")
            keys.setdefault(mask_req.key, mask_req)
        return keys

    def _synthesize_mask(self, req: ColumnRequest) -> bool:
        if req.repr != "mask" or not self._is_all_valid(req.column):
            return False
        from deequ_tpu import config

        return config.options().synthesize_all_true_masks

    def _column_arrow_type(self, column: str) -> pa.DataType:
        """Storage-type hook (parquet sources answer from file schema)."""
        return self._table.column(column).type

    def _request_row_bytes(self, r: ColumnRequest) -> int:
        """Device bytes per row for one request (0 for synthesized);
        mirrors what materialize() actually produces, not the Arrow
        storage width (timestamps/dates widen to int64, f16 to f32;
        codes/int64 values may be wire-narrowed). Unmaterialized
        estimates are conservative upper bounds."""
        if r.repr == "mask":
            return 0 if self._synthesize_mask(r) else 1
        cached = self._materialized.get(r.key)
        if cached is not None:
            return cached.dtype.itemsize  # the true narrowed width
        if r.repr in ("codes", "lengths"):
            return 4
        if r.repr == "u64bits":
            return 8
        kind = self._schema.kind_of(r.column)
        if kind in (Kind.BOOLEAN, Kind.STRING):
            return 4
        if kind == Kind.TIMESTAMP:
            return 8
        try:
            width = max(1, self._column_arrow_type(r.column).bit_width // 8)
        except (ValueError, AttributeError):
            return 8
        return max(width, 4)  # f16 materializes as f32

    def dictionary_size_within(
        self, column: str, cap: int
    ) -> Optional[int]:
        """Distinct-value count if it is <= cap, else None WITHOUT
        necessarily building the full dictionary (parquet sources bail
        out of the streaming pre-pass once the cap is passed, so a
        spilling plan never materializes an unbounded value set)."""
        d = self.dictionary(column)
        return len(d) if len(d) <= cap else None

    def integral_range(
        self, column: str
    ) -> Optional[Tuple[int, int]]:
        """(min, max) of an INTEGRAL column in one vectorized Arrow
        pass — O(1) host memory, NO distinct set. Lets planners detect
        a bounded value domain (TPC-DS quantity-style columns) without
        the unbounded host dictionary the spill gate exists to avoid.
        None for non-integral columns or all-null data. Cached: the
        grouping planner asks once per (column, run)."""
        if self._schema.kind_of(column) != Kind.INTEGRAL:
            return None
        if not hasattr(self, "_integral_ranges"):
            self._integral_ranges: Dict[
                str, Optional[Tuple[int, int]]
            ] = {}
        if column not in self._integral_ranges:
            arr = self._table.column(column)
            if pa.types.is_dictionary(arr.type):
                self._integral_ranges[column] = None
            else:
                mm = pc.min_max(arr)
                lo, hi = mm["min"].as_py(), mm["max"].as_py()
                self._integral_ranges[column] = (
                    None
                    if lo is None or hi is None
                    else (int(lo), int(hi))
                )
        return self._integral_ranges[column]

    def _derived_length_codes(
        self, keys: Dict[str, ColumnRequest]
    ) -> List[ColumnRequest]:
        """Codes requests the derived-lengths path would ADD to the
        cache beyond the request set itself (a 'lengths' request served
        by LUT gather pins the column's codes chunks too) — the budget
        accounting must see them or eviction under-frees."""
        extra = []
        for r in keys.values():
            if r.repr != "lengths":
                continue
            try:
                if self._schema.kind_of(r.column) != Kind.STRING:
                    continue
            except KeyError:
                continue
            codes_key = f"{r.column}::codes"
            if codes_key in keys:
                continue
            if (
                codes_key in self._materialized
                or r.column in self._dictionaries
            ):
                extra.append(ColumnRequest(r.column, "codes"))
        return extra

    def estimated_device_bytes(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: int,
        chunk_batches: int = 1,
        derive_lengths: bool = True,
    ) -> int:
        """Upper-bound device bytes for the resident scan path (padded
        to whole chunks; all-valid masks cost nothing — they alias the
        synthesized row mask; derived string lengths pin their codes
        chunks too). ``derive_lengths`` mirrors device_scan_chunks'
        ``sharding is None`` gate: under explicit sharding lengths ship
        directly, so the extra codes chunks must NOT be counted or
        meshed scans over-estimate and wrongly reject the resident
        path / over-evict (ADVICE r3)."""
        _, n_chunks = self._chunk_geometry(batch_size, chunk_batches)
        padded = n_chunks * chunk_batches * batch_size
        keys = self._dedup_requests(requests)
        per_row = 1  # synthesized row mask
        for r in keys.values():
            per_row += self._request_row_bytes(r)
        if derive_lengths:
            for r in self._derived_length_codes(keys):
                per_row += self._request_row_bytes(r)
        return padded * per_row

    def _chunk_geometry(
        self, batch_size: int, chunk_batches: int
    ) -> Tuple[int, int]:
        """(num_batches, num_chunks). The last chunk is padded with
        whole batches whose rows are all masked off (static chunk shape
        -> one compile serves every chunk)."""
        nb = self.num_batches(batch_size)
        return nb, max(1, -(-nb // chunk_batches))

    def _uncached_bytes(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: int,
        chunk_batches: int,
        shard_key,
    ) -> int:
        """DEVICE (HBM) bytes this request set would ADD to the cache
        (keys already resident are free — the eviction test must not
        count them, or re-scans of a cached set would evict themselves).
        Masks count at their unpacked resident width (1 byte/row); wire
        bytes are tracked separately via add_transfer_bytes."""
        _, n_chunks = self._chunk_geometry(batch_size, chunk_batches)
        chunk_rows = chunk_batches * batch_size
        keys = self._dedup_requests(requests)
        counted = dict(keys)
        if shard_key is None:  # derived lengths only ride the
            # unsharded path (device_scan_chunks gates on sharding)
            for r in self._derived_length_codes(keys):
                counted.setdefault(r.key, r)
        total = 0
        for ci in range(n_chunks):
            if (
                ROW_MASK, batch_size, chunk_batches, ci, shard_key
            ) not in self._device_cache:
                total += chunk_rows
            for k, r in counted.items():
                if self._synthesize_mask(r):
                    continue
                if (
                    k, batch_size, chunk_batches, ci, shard_key
                ) in self._device_cache:
                    continue
                total += chunk_rows * self._request_row_bytes(r)
        return total

    def _ensure_cache_budget(self, needed: int, budget: int) -> None:
        """Evict device caches (other datasets first, LRU order, then
        this one) until ``needed`` more bytes fit in ``budget``."""
        if Dataset.global_device_cache_bytes() + needed <= budget:
            return
        for key in list(Dataset._cache_registry):
            if key == self._cache_key:
                continue
            ref = Dataset._cache_registry[key]
            ds = ref()
            if ds is not None:
                ds.clear_device_cache()
            else:
                Dataset._drop_cache_key(key)
            if Dataset.global_device_cache_bytes() + needed <= budget:
                return
        if Dataset.global_device_cache_bytes() + needed > budget:
            self.clear_device_cache()

    def _host_chunk(
        self, r: ColumnRequest, start_row: int, chunk_rows: int, batch_size: int
    ) -> np.ndarray:
        """(chunk_batches, batch_size) host array for one request's
        chunk: a slice of the materialized column, zero-padded (padding
        rows carry mask False exactly like the host batch path)."""
        full = self.materialize(r)
        n = len(full)
        stop = min(start_row + chunk_rows, n)
        sl = full[start_row:stop] if start_row < n else full[:0]
        if len(sl) < chunk_rows:
            sl = np.concatenate(
                [sl, np.zeros((chunk_rows - len(sl),), dtype=full.dtype)]
            )
        return sl.reshape(-1, batch_size)

    def device_scan_chunks(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: int,
        chunk_batches: int = 1,
        sharding=None,
        budget_bytes: int = 0,
        start_chunk: int = 0,
    ) -> Iterator[Dict[str, "object"]]:
        """Device-resident stacked batches for the fused ``lax.scan``
        path, yielded chunk by chunk: each chunk is a dict of
        ``(chunk_batches, batch_size)`` jax arrays. ``start_chunk``
        skips the first N chunks (resilience-layer retry/resume; chunk
        geometry is independent of the start, so chunk ``i`` is
        identical whatever chunk the iteration began at).

        Chunking is what lets a FRESH-data run overlap transfer with
        compute: ``device_put`` and the per-chunk scan dispatch are both
        async, so while the device crunches chunk i, chunk i+1's bytes
        stream over the (bottleneck) host->device link — wall becomes
        max(transfer, compute) instead of their sum (VERDICT.md r2 weak
        #4). Every chunk is cached on device, so a re-scan replays from
        HBM with zero transfers.

        Wire-byte diet (the tunnel link is the engine's bottleneck):
        - validity masks ship BIT-packed (np.packbits host-side, 8x
          fewer bytes) and are expanded on device;
        - masks of all-valid columns and the row mask are synthesized on
          device via iota — they never cross the wire;
        - string 'lengths' are derived on device from dictionary codes +
          a tiny length LUT whenever the codes ship anyway.

        When adding this request set would push the resident total past
        ``budget_bytes``, older cache entries are evicted first (the new
        set alone is known to fit — the engine checks before choosing
        this path).
        """
        import jax

        n = self.num_rows
        nb, n_chunks = self._chunk_geometry(batch_size, chunk_batches)
        chunk_rows = chunk_batches * batch_size

        # NamedSharding hashes by value, so equal shardings share entries
        shard_key = sharding

        if budget_bytes:
            self._ensure_cache_budget(
                self._uncached_bytes(
                    requests, batch_size, chunk_batches, shard_key
                ),
                budget_bytes,
            )
        self._touch_cache_registry()

        def put(host: np.ndarray):
            add_transfer_bytes(host.nbytes)
            if sharding is not None:
                # lint-ok: wire-discipline: the chunk-cache put IS the
                # resident wire (packed chunks, transfer accounted)
                return jax.device_put(host, sharding)
            # lint-ok: wire-discipline: resident wire put (see above)
            return jax.device_put(host)

        keys = self._dedup_requests(requests)
        # wire-free lengths: string columns whose codes ship anyway (or
        # are already materialized) gather lengths from a LUT on device.
        # Disabled under explicit sharding (LUT gather output placement
        # would need its own annotation; the mesh path ships lengths).
        derived_lengths: Dict[str, np.ndarray] = {}
        if sharding is None:
            for k, r in keys.items():
                if r.repr != "lengths":
                    continue
                if self._schema.kind_of(r.column) != Kind.STRING:
                    continue
                codes_key = f"{r.column}::codes"
                if codes_key in keys:
                    # codes ship anyway: materialize them NOW so the
                    # dictionary (and its length LUT) exists — without
                    # this the branch only fired when some earlier
                    # caller had happened to materialize codes first
                    self.materialize(ColumnRequest(r.column, "codes"))
                if (
                    codes_key in self._materialized
                    or r.column in self._dictionaries
                ):
                    lengths = self.dict_lengths(r.column)
                    if lengths is not None:
                        derived_lengths[r.column] = lengths

        lut_cache: Dict[str, object] = {}
        pack_masks = sharding is None

        for ci in range(start_chunk, n_chunks):
            start_row = ci * chunk_rows
            rm_key = (ROW_MASK, batch_size, chunk_batches, ci, shard_key)
            if rm_key not in self._device_cache:
                if sharding is not None:
                    idx = np.arange(
                        start_row,
                        start_row + chunk_rows,
                        dtype=np.int64,
                    )
                    row_mask = put((idx < n).reshape(-1, batch_size))
                else:
                    row_mask = _chunk_row_mask_fn(chunk_batches, batch_size)(
                        np.int64(start_row), np.int64(n)
                    )
                self._device_cache[rm_key] = row_mask
                self._add_cache_bytes(chunk_rows)
            row_mask = self._device_cache[rm_key]

            out: Dict[str, object] = {ROW_MASK: row_mask}
            for k, r in keys.items():
                if self._synthesize_mask(r):
                    out[k] = row_mask
                    continue
                ck = (k, batch_size, chunk_batches, ci, shard_key)
                if ck not in self._device_cache:
                    if r.repr == "lengths" and r.column in derived_lengths:
                        codes_req = ColumnRequest(r.column, "codes")
                        codes_ck = (
                            codes_req.key, batch_size, chunk_batches, ci,
                            shard_key,
                        )
                        if codes_ck not in self._device_cache:
                            codes_host = self._host_chunk(
                                codes_req, start_row, chunk_rows, batch_size
                            )
                            self._device_cache[codes_ck] = put(codes_host)
                            self._add_cache_bytes(codes_host.nbytes)
                        if r.column not in lut_cache:
                            lengths = derived_lengths[r.column]
                            lut = np.concatenate(
                                [np.zeros(1, np.int32), lengths]
                            )
                            lut_cache[r.column] = put(lut)
                        arr = _lengths_gather_fn()(
                            self._device_cache[codes_ck],
                            lut_cache[r.column],
                        )
                    elif r.repr == "mask" and pack_masks:
                        host = self._host_chunk(
                            r, start_row, chunk_rows, batch_size
                        )
                        packed = np.packbits(
                            host, axis=1, bitorder="little"
                        )
                        arr = _mask_unpack_fn(batch_size)(put(packed))
                    else:
                        host = self._host_chunk(
                            r, start_row, chunk_rows, batch_size
                        )
                        arr = put(host)
                    self._device_cache[ck] = arr
                    self._add_cache_bytes(
                        chunk_rows * self._request_row_bytes(r)
                    )
                out[k] = self._device_cache[ck]
            yield out

    def clear_device_cache(self) -> None:
        self._device_cache.clear()
        Dataset._drop_cache_key(self._cache_key)

    def num_batches(self, batch_size: Optional[int] = None) -> int:
        n = self.num_rows
        if n == 0:
            return 1
        if batch_size is None:
            return 1
        return -(-n // batch_size)

    def fingerprint(self) -> str:
        """Source identity for checkpoint invalidation (resuming a scan
        against a CHANGED source would silently fold two datasets into
        one metric). In-memory tables have no stable storage identity,
        so this is a WEAK fingerprint — schema + row count + a sample
        of the first column's bytes; parquet sources override with file
        paths/sizes/mtimes. docs/RESILIENCE.md documents the contract."""
        h = hashlib.sha1()
        h.update(
            repr(
                [(f.name, f.kind.value) for f in self._schema.fields]
            ).encode()
        )
        h.update(str(self.num_rows).encode())
        if self.num_rows and len(self._schema):
            first = self._schema.fields[0].name
            head = self._table.column(first).slice(
                0, min(self.num_rows, 1024)
            )
            h.update(repr(head.to_pylist()).encode())
        return f"mem-{h.hexdigest()[:20]}"
