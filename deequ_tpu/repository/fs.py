"""Filesystem metrics repository: one JSON file of all results.

Reference: ``repository/fs/FileSystemMetricsRepository.scala`` (SURVEY.md
§2.5) — JSON file on local/HDFS/S3 via the Hadoop FS API; here plain
paths use the local filesystem and ``scheme://`` URIs route through
deequ_tpu.io.storage's backend registry (``mem://`` ships in-tree;
cloud backends register in a few lines — VERDICT r3 missing #5).
Concurrent writers are serialized by an advisory in-process lock plus,
on local filesystems, an ``fcntl.flock`` cross-process lock (two worker
processes appending to the same repository file would otherwise lose
updates in the read-modify-write); the file is rewritten with atomic
visibility (Storage.write_bytes).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import List, Optional

from deequ_tpu.io.storage import LocalStorage, interprocess_lock, storage_for
from deequ_tpu.repository import base, serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        if "://" in path:
            # URI: the final segment is the blob key, the rest is the
            # storage root (s3://bucket/dir/metrics.json)
            root, _, self._key = path.rpartition("/")
            if "://" not in root or root.endswith("//") or not self._key:
                # e.g. "mem://metrics.json": no root segment left —
                # refuse rather than silently treating "mem:/" as a
                # local directory
                raise ValueError(
                    "a URI repository path needs at least "
                    "scheme://root/key (the final segment is the "
                    f"blob name): got {path!r}"
                )
            self._storage = storage_for(root)
        else:
            parent = os.path.dirname(os.path.abspath(path)) or "."
            self._key = os.path.basename(path)
            if not self._key:
                # a trailing separator ('dir/') leaves an empty blob
                # name — refuse like the URI branch does rather than
                # silently reading/writing the directory root
                raise ValueError(
                    "a repository path must name a file, not a "
                    f"directory: got {path!r}"
                )
            self._storage = storage_for(parent)

    @contextlib.contextmanager
    def _process_lock(self):
        """Cross-process flock on local storage (sidecar ``.lock`` file
        next to the repository file); remote backends rely on their own
        consistency model, so only the in-process lock applies there."""
        if isinstance(self._storage, LocalStorage):
            lock_path = os.path.join(
                self._storage.root, self._key + ".lock"
            )
            with interprocess_lock(lock_path):
                yield
        else:
            yield

    def _read_all(self) -> List[AnalysisResult]:
        raw = self._storage.read_bytes(self._key)
        if raw is None:
            return []
        try:
            text = raw.decode()
            if not text.strip():
                return []
            return serde.deserialize(text)
        except Exception:  # noqa: BLE001 — crash-safety: a partial or
            # corrupt repository file (e.g. from a kill mid-write on a
            # backend without atomic replace) reads as empty instead of
            # poisoning every subsequent run; the next save rewrites it
            from deequ_tpu.telemetry import get_telemetry

            tm = get_telemetry()
            tm.counter("repository.corrupt_files").inc()
            tm.event("repository_corrupt_file", path=self._path)
            return []

    def _write_all(self, results: List[AnalysisResult]) -> None:
        self._storage.write_bytes(
            self._key, serde.serialize(results).encode()
        )

    def save(self, result: AnalysisResult) -> None:
        base._bump("repository.saves")
        with self._lock, self._process_lock():
            results = [
                r
                for r in self._read_all()
                if r.result_key != result.result_key
            ]
            results.append(result)
            self._write_all(results)

    def load_by_key(self, key: ResultKey) -> Optional[AnalysisResult]:
        base._bump("repository.loads")
        with self._lock, self._process_lock():
            for result in self._read_all():
                if result.result_key == key:
                    return result
        return None

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        base._bump("repository.loads")
        with self._lock, self._process_lock():
            return MetricsRepositoryMultipleResultsLoader(self._read_all())
