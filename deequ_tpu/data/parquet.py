"""Streaming parquet ingest: scan-feed batches without materializing
whole columns.

Reference context: the reference delegates IO to Spark's parquet reader
feeding partitioned scans (SURVEY.md §7 stage 0, §5.7 "streamed
chunking over record batches"). Here :class:`ParquetDataset` exposes
the same Dataset contract over a (multi-file) parquet source:

- ``device_batches`` STREAMS: Arrow record batches are read column-
  pruned from the files, re-chunked to the engine's fixed batch size,
  converted to device representations per batch, and fed to the fused
  scan — host memory stays O(batch x requested columns), so a table
  far larger than RAM profiles fine.
- string columns: under ``config.dict_deltas`` (default) the global
  dictionary is built INCREMENTALLY inside the same pass — each batch
  absorbs its new uniques into a per-column accumulator, codes index
  against the accumulator, and only the DELTA (new uniques, appended
  in first-occurrence order) rides the batch as a host-only payload
  (table.DICT_DELTA_PREFIX) for delta-aware ops to fold into their
  LUT states. Incremental accumulation provably reproduces the exact
  dictionary (values AND order) of the legacy streaming pre-pass
  (``_collect_uniques``), independent of chunking — which is why
  delta codes and pre-pass codes are interchangeable and a stable
  dictionary costs zero bytes after batch 1. With the flag off, the
  legacy one-extra-pass pre-pass builds the dictionary up front.
- ``materialize`` (full column) still works — the resident fast path
  uses it when the request set fits the device cache budget — but the
  streaming path never calls it.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.dataset as pads

from deequ_tpu.data.table import (
    ColumnRequest,
    DICT_DELTA_PREFIX,
    Dataset,
    Field,
    Kind,
    ROW_MASK,
    Schema,
    _kind_of,
    convert_basic_repr,
    dictionary_to_numpy,
    narrow_codes,
)


class _IncrementalDict:
    """One column's global dictionary, grown batch-by-batch inside the
    single data pass. ``absorb_and_encode`` appends a batch's new
    uniques (first-occurrence order — provably the same dictionary,
    values and order, that ``_collect_uniques`` builds over the same
    row stream, whatever the chunking) and returns the batch's int32
    codes against the grown accumulator, so a row's code is always
    valid against every dictionary state at or after its batch."""

    __slots__ = ("values", "n")

    def __init__(self) -> None:
        self.values: Optional[pa.Array] = None
        self.n = 0

    def absorb_and_encode(self, column: pa.Array) -> np.ndarray:
        if pa.types.is_dictionary(column.type):
            column = pc.cast(column, column.type.value_type)
        self.absorb(pc.drop_null(pc.unique(column)))
        return self.encode(column)

    # r10 split: the ordered ingest pool computes a batch's uniques on
    # a WORKER thread (pc.unique is order-free) and runs absorb+encode
    # on the consumer at ordered release, so accumulator growth — the
    # one order-dependent step — happens exactly as the single-thread
    # path would. absorb(unique(b0)); absorb(unique(b1)); ... yields
    # the identical first-occurrence dictionary whatever the chunking.

    def absorb(self, uniques: pa.Array) -> None:
        """Append a batch's new uniques in first-occurrence order."""
        if len(uniques):
            if self.values is None:
                self.values = uniques
            else:
                idx = pc.index_in(uniques, value_set=self.values)
                new = uniques.filter(pc.is_null(idx))
                if len(new):
                    self.values = pa.concat_arrays([self.values, new])
            self.n = len(self.values)

    def encode(self, column: pa.Array) -> np.ndarray:
        """int32 codes against the accumulator as absorbed so far
        (nulls and unseen values index to -1)."""
        if pa.types.is_dictionary(column.type):
            column = pc.cast(column, column.type.value_type)
        if self.values is None or self.n == 0:
            return np.full(len(column), -1, dtype=np.int32)
        idx = pc.index_in(column, value_set=self.values)
        idx = pc.fill_null(idx, pa.scalar(-1, idx.type))
        return np.ascontiguousarray(
            idx.to_numpy(zero_copy_only=False).astype(np.int32)
        )

    def slice_values(self, start: int) -> np.ndarray:
        """Accumulated values [start, n) as a host numpy array — one
        delta payload's ``values``."""
        return dictionary_to_numpy(
            self.values.slice(start, self.n - start)
        )


def _column_batch_to_reprs(
    column: pa.Array,
    kind: Kind,
    requests: List[str],
    value_set: Optional[pa.Array] = None,
    values_dtype: Optional[np.dtype] = None,
) -> Dict[str, np.ndarray]:
    """Convert one record-batch column into the requested device reprs.
    mask/values/lengths/u64bits share Dataset.materialize's conversion
    rules (table.convert_basic_repr); codes come from a vectorized
    ``pc.index_in`` against the dataset-global dictionary (Arrow treats
    NaN as equal to NaN, matching the in-memory dictionary_encode
    path; nulls index to -1). ``values_dtype`` applies the PER-COLUMN
    wire-narrowing decision (from parquet statistics) — narrowing per
    batch would make streamed batch dtypes unstable and recompile the
    fused scan per dtype combination."""
    out: Dict[str, np.ndarray] = {}
    for repr_name in requests:
        if repr_name == "codes":
            assert value_set is not None
            if pa.types.is_dictionary(column.type):
                column = pc.cast(column, column.type.value_type)
            idx = pc.index_in(column, value_set=value_set)
            idx = pc.fill_null(idx, pa.scalar(-1, idx.type))
            out["codes"] = np.ascontiguousarray(
                # lint-ok: wire-discipline: loop is over the REPRS of
                # one column, not batches; the width derives from the
                # run-stable global value_set, identical every batch
                narrow_codes(
                    idx.to_numpy(zero_copy_only=False).astype(np.int32),
                    len(value_set),
                )
            )
        else:
            arr = convert_basic_repr(column, kind, repr_name)
            if repr_name == "values" and values_dtype is not None:
                arr = arr.astype(values_dtype)
            out[repr_name] = arr
    return out


class ParquetDataset(Dataset):
    """A Dataset over parquet file(s)/directory, scanned lazily."""

    # r10: class-level opt-in for the ordered ingest pool — the engine
    # engages ``ingest_work_items`` only on classes that declare this
    # (a __getattr__-delegating wrapper must define its own planner)
    supports_parallel_ingest = True

    def __init__(self, source, read_batch_rows: int = 1 << 20):
        # no super().__init__: there is no in-memory table
        # a prebuilt pyarrow dataset (the shard_view planner's
        # row-group-restricted FileSystemDataset) passes through as-is
        self._source = (
            source
            if isinstance(source, pads.Dataset)
            else pads.dataset(source, format="parquet")
        )
        self._shard_tag = None
        self._read_batch_rows = read_batch_rows
        self._schema = Schema(
            tuple(
                Field(name, _kind_of(typ))
                for name, typ in zip(
                    self._source.schema.names, self._source.schema.types
                )
            )
        )
        self._num_rows = self._source.count_rows()
        self._materialized: Dict[str, np.ndarray] = {}
        self._dictionaries: Dict[str, np.ndarray] = {}
        # one-pass dictionary deltas: per-column incremental
        # accumulators (persist across device_batches calls so a
        # restart resumes the grown dictionary) and the set of columns
        # COMMITTED to delta mode by a plan-time dict_delta_capacity
        # consultation
        self._delta_dicts: Dict[str, _IncrementalDict] = {}
        self._delta_columns: set = set()
        self._value_sets: Dict[str, pa.Array] = {}
        self._null_counts: Dict[str, int] = {}
        self._device_cache: Dict = {}
        self._cache_key = id(self)
        import weakref

        weakref.finalize(self, Dataset._drop_cache_key, self._cache_key)

    # -- metadata -------------------------------------------------------

    @property
    def table(self) -> pa.Table:  # loads everything; avoid on big data
        return self._source.to_table()

    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def num_columns(self) -> int:
        return len(self._schema)

    def filter_rows(self, mask: np.ndarray) -> Dataset:
        return Dataset(self.table.filter(pa.array(mask)))

    def select(self, columns: Sequence[str]) -> Dataset:
        return Dataset(self._source.to_table(columns=list(columns)))

    def record_batches(
        self, columns: Sequence[str], batch_rows: int = 1 << 20
    ) -> Iterator[pa.RecordBatch]:
        scanner = self._source.scanner(
            columns=list(columns), batch_size=batch_rows
        )
        return iter(scanner.to_batches())

    def fingerprint(self) -> str:
        """STRONG source identity for checkpoint invalidation: the
        sorted file list plus per-file size and mtime (rewritten,
        appended or touched files all change it). Falls back to
        path-only identity for storage without stat support."""
        import hashlib
        import os

        h = hashlib.sha1()
        for path in sorted(self._source.files):
            h.update(path.encode())
            try:
                st = os.stat(path)
                h.update(f":{st.st_size}:{st.st_mtime_ns}".encode())
            except OSError:
                pass
        if self._shard_tag is not None:
            # two shard views of the SAME files must not share a
            # checkpoint identity (their row streams differ)
            h.update(
                f"shard:{self._shard_tag[0]}/{self._shard_tag[1]}".encode()
            )
        h.update(str(self._num_rows).encode())
        return f"parquet-{h.hexdigest()[:20]}"

    # -- statistics from parquet metadata -------------------------------

    def _column_null_count(self, column: str) -> int:
        if column not in self._null_counts:
            total = 0
            known = True
            for fragment in self._source.get_fragments():
                meta = fragment.metadata
                idx = self._source.schema.get_field_index(column)
                for rg in range(meta.num_row_groups):
                    stats = meta.row_group(rg).column(idx).statistics
                    if stats is None or stats.null_count is None:
                        known = False
                        break
                    total += stats.null_count
                if not known:
                    break
            # unknown stats -> conservatively "has nulls" (mask ships)
            self._null_counts[column] = total if known else 1
        return self._null_counts[column]

    def _is_all_valid(self, column: str) -> bool:
        return self._column_null_count(column) == 0

    def _values_dtype(self, column: str) -> Optional[np.dtype]:
        """Per-COLUMN wire-narrowing decision for int64 columns, from
        parquet row-group min/max statistics (one decision for the whole
        stream; see _column_batch_to_reprs). None = keep native."""
        if not hasattr(self, "_values_dtypes"):
            self._values_dtypes: Dict[str, Optional[np.dtype]] = {}
        if column in self._values_dtypes:
            return self._values_dtypes[column]
        decision: Optional[np.dtype] = None
        arrow_type = self._column_arrow_type(column)
        if (
            self._schema.kind_of(column) == Kind.INTEGRAL
            and pa.types.is_integer(arrow_type)
            and arrow_type.bit_width == 64
        ):
            rng = self._stats_min_max(column)
            if (
                rng is not None
                and rng[0] >= -(2**31)
                and rng[1] < 2**31
            ):
                decision = np.dtype(np.int32)
        self._values_dtypes[column] = decision
        return decision

    def _stats_min_max(self, column: str):
        """(min, max) folded over every fragment's row-group
        statistics, or None when any group lacks them — THE one stats
        walk (consumed by the wire-narrowing decision above and the
        integral-range probe below)."""
        lo, hi = None, None
        idx = self._source.schema.get_field_index(column)
        for fragment in self._source.get_fragments():
            meta = fragment.metadata
            for rg in range(meta.num_row_groups):
                stats = meta.row_group(rg).column(idx).statistics
                if (
                    stats is None
                    or not stats.has_min_max
                    or stats.min is None
                    or stats.max is None
                ):
                    return None
                lo = stats.min if lo is None else min(lo, stats.min)
                hi = stats.max if hi is None else max(hi, stats.max)
        return None if lo is None else (lo, hi)

    def _column_arrow_type(self, column: str) -> pa.DataType:
        idx = self._source.schema.get_field_index(column)
        return self._source.schema.types[idx]

    def request_dtype(self, req: ColumnRequest) -> np.dtype:
        """Batch dtype WITHOUT materializing the stream: run the one
        authoritative conversion (_column_batch_to_reprs) on a ZERO-ROW
        column of the file's type, so any future change to the
        conversion/narrowing rules is reflected here automatically."""
        if req.repr == "mask":
            return np.dtype(bool)
        if req.repr == "codes" and self._dict_delta_mode(req.column):
            # delta-mode codes are canonical int32 on every path (the
            # wire codec layer narrows them on the wire); crucially
            # this answers WITHOUT the dictionary pre-pass — plan
            # building must stay zero-pass
            return np.dtype(np.int32)
        kind = self._schema.kind_of(req.column)
        value_set = (
            self._dict_value_set(req.column)
            if req.repr == "codes"
            else None
        )
        values_dtype = (
            self._values_dtype(req.column)
            if req.repr == "values"
            else None
        )
        empty = pa.array([], type=self._column_arrow_type(req.column))
        out = _column_batch_to_reprs(
            empty, kind, [req.repr], value_set, values_dtype
        )
        return np.dtype(out[req.repr].dtype)

    # -- one-pass dictionary deltas --------------------------------------

    def _dict_delta_mode(self, column: str) -> bool:
        """True when this column's codes ship as incremental dictionary
        deltas inside the single data pass (docs/PERF.md "One-pass
        dictionary deltas") instead of via the legacy pre-pass. A
        column COMMITTED by ``dict_delta_capacity`` stays in delta mode
        for run-long consistency; otherwise the flag and the kind
        decide — except when an already-cached dictionary is too big
        for the delta LUT capacity, where the free consts path wins."""
        if column in self._delta_columns:
            return True
        from deequ_tpu import config

        opts = config.options()
        if not opts.dict_deltas:
            return False
        if self._schema.kind_of(column) != Kind.STRING:
            return False
        d = self._dictionaries.get(column)
        if d is not None and len(d) > opts.dict_delta_capacity:
            return False
        return True

    def dict_delta_capacity(self, column: str) -> Optional[int]:
        """Static delta-LUT capacity for delta-aware consumers at PLAN
        time (None: this column's codes will not ship deltas — build
        the consts-LUT form). Consulting this COMMITS the column: once
        a plan holds a delta-aware op sized to the capacity,
        ``device_batches`` must ship deltas for it on every call."""
        if not self._dict_delta_mode(column):
            return None
        self._delta_columns.add(column)
        from deequ_tpu import config

        return int(config.options().dict_delta_capacity)

    # -- global dictionaries (streaming pre-pass) -----------------------

    def _collect_uniques(
        self, column: str, cap: Optional[int]
    ) -> Optional[pa.Array]:
        """Stream distinct non-null values, staying ENTIRELY in Arrow
        (pc.unique per chunk, periodic compaction) — a Python set would
        cost GBs at tens of millions of distinct values. Returns None
        once the count provably exceeds ``cap``."""
        # an HONEST pass counter: this pre-pass reads the whole column,
        # so one-pass claims (tests/test_wire_codecs.py) can pin that
        # delta-mode suites never reach here
        from deequ_tpu.telemetry import get_telemetry

        get_telemetry().counter("engine.data_passes").inc()
        base: Optional[pa.Array] = None  # already-deduped accumulator
        fresh: List[pa.Array] = []  # per-batch uniques since last compact
        fresh_n = 0

        def compact() -> None:
            nonlocal base, fresh, fresh_n
            arrays = ([base] if base is not None else []) + fresh
            base = pc.unique(pa.concat_arrays(arrays))
            fresh = []
            fresh_n = 0

        scanner = self._source.scanner(
            columns=[column], batch_size=self._read_batch_rows
        )
        field_type = self._source.schema.field(column).type
        if pa.types.is_dictionary(field_type):
            field_type = field_type.value_type
        for batch in scanner.to_batches():
            col = batch.column(0)
            if pa.types.is_dictionary(col.type):
                col = pc.cast(col, col.type.value_type)
            u = pc.drop_null(pc.unique(col))
            if len(u):
                fresh.append(u)
                fresh_n += len(u)
            # compact on FRESH volume only (an accumulator already past
            # the threshold must not trigger a full re-unique per batch),
            # or when the optimistic total might prove the cap exceeded
            over_cap_maybe = cap is not None and (
                (0 if base is None else len(base)) + fresh_n > cap
            )
            if fresh_n > 4 * self._read_batch_rows or over_cap_maybe:
                compact()
                if cap is not None and len(base) > cap:
                    return None
        if fresh_n:
            compact()
        if base is None:
            return pa.array([], field_type)
        if cap is not None and len(base) > cap:
            return None
        return base

    def integral_range(self, column: str):
        """Row-group min/max statistics make the range probe FREE for
        parquet sources (no data scan); unknown stats -> None (treated
        as unbounded)."""
        if self._schema.kind_of(column) != Kind.INTEGRAL:
            return None
        if not hasattr(self, "_integral_ranges"):
            self._integral_ranges = {}
        if column not in self._integral_ranges:
            rng = self._stats_min_max(column)
            self._integral_ranges[column] = (
                (int(rng[0]), int(rng[1]))
                if rng is not None and isinstance(rng[0], int)
                else None
            )
        return self._integral_ranges[column]

    def dictionary_size_within(self, column: str, cap: int):
        if column in self._dictionaries:
            n = len(self._dictionaries[column])
            return n if n <= cap else None
        uniques = self._collect_uniques(column, cap)
        if uniques is None:
            return None  # over cap: never materialize the full set
        self._store_dictionary(column, uniques)
        return len(self._dictionaries[column])

    def _store_dictionary(self, column: str, uniques: pa.Array) -> None:
        self._value_sets[column] = uniques
        self._dictionaries[column] = dictionary_to_numpy(uniques)

    def dictionary(self, column: str) -> np.ndarray:
        if column not in self._dictionaries:
            self._store_dictionary(
                column, self._collect_uniques(column, None)
            )
        return self._dictionaries[column]

    def _dict_value_set(self, column: str) -> pa.Array:
        self.dictionary(column)
        return self._value_sets[column]

    # -- full-column materialization (resident path only) ---------------

    def _reprs_for_kind(self, kind: Kind) -> List[str]:
        """All reprs one scan can fill for a column of this kind —
        materializing any repr fills the others too, so callers needing
        several (values+mask, codes+mask+lengths) cost ONE file scan."""
        if kind == Kind.STRING:
            return ["codes", "mask", "lengths"]
        return ["values", "mask"]

    def materialize(self, req: ColumnRequest) -> np.ndarray:
        key = req.key
        if key in self._materialized:
            return self._materialized[key]
        kind = self._schema.kind_of(req.column)
        reprs = self._reprs_for_kind(kind)
        if req.repr not in reprs:
            reprs = reprs + [req.repr]  # let the converter raise clearly
        value_set = (
            self._dict_value_set(req.column) if "codes" in reprs else None
        )
        chunks: Dict[str, List[np.ndarray]] = {r: [] for r in reprs}
        scanner = self._source.scanner(
            columns=[req.column], batch_size=self._read_batch_rows
        )
        values_dtype = self._values_dtype(req.column)
        for batch in scanner.to_batches():
            out = _column_batch_to_reprs(
                batch.column(0), kind, reprs, value_set, values_dtype
            )
            for r in reprs:
                chunks[r].append(out[r])
        for r in reprs:
            if chunks[r]:
                arr = np.concatenate(chunks[r])
            else:
                arr = _column_batch_to_reprs(
                    pa.array([], self._source.schema.field(req.column).type),
                    kind,
                    [r],
                    value_set,
                    values_dtype,
                )[r]
            if r == "codes" and self._dict_delta_mode(req.column):
                # delta-committed codes are canonical int32 on EVERY
                # path, so resident and streaming plans of the same
                # suite see one dtype (request_dtype above agrees)
                arr = arr.astype(np.int32)
            self._materialized[f"{req.column}::{r}"] = arr
        return self._materialized[key]

    # -- streaming batches ----------------------------------------------

    def device_batches(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: Optional[int] = None,
        start_batch: int = 0,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Stream fixed-size batches from the parquet source: read
        column-pruned record batches, convert to device reprs, re-chunk
        to ``batch_size``, zero-pad the tail. Host memory is bounded by
        O(read_batch + batch_size) per requested repr.

        ``start_batch`` (resilience-layer retry/resume) skips the first
        ``start_batch * batch_size`` rows of the stream by slicing the
        leading record batches away before any conversion; since the
        skip is a whole number of engine batches, the re-chunker's batch
        boundaries — and therefore every yielded batch — are identical
        to the corresponding batches of a full stream."""
        n = self.num_rows
        if batch_size is None:
            batch_size = n if n > 0 else 1
        batch_size = max(1, batch_size)
        skip_rows = start_batch * batch_size

        keys = self._dedup_requests(requests)
        by_column: Dict[str, List[str]] = {}
        for r in keys.values():
            by_column.setdefault(r.column, []).append(r.repr)
        columns = sorted(by_column)
        if not columns or n == 0:
            # degenerate: no columns requested (e.g. Size only) or empty
            yield from self._empty_or_counting_batches(
                keys, batch_size, n, skip_rows
            )
            return
        # one-pass dictionary deltas: delta-mode columns build their
        # dictionary INSIDE this pass and ship only deltas; everything
        # else keeps the legacy streaming pre-pass
        delta_cols = sorted(
            c
            for c, reprs in by_column.items()
            if "codes" in reprs and self._dict_delta_mode(c)
        )
        accs = {
            c: self._delta_dicts.setdefault(c, _IncrementalDict())
            for c in delta_cols
        }
        # per-CALL delta cursors: a fresh call (restart or resume)
        # re-ships the full accumulated dictionary on its first yielded
        # batch — idempotent by construction, since delta application
        # overwrites LUT rows with values hashed/classified from the
        # values themselves
        shipped_n = {c: 0 for c in delta_cols}
        # pre-build dictionaries for remaining code requests
        value_sets = {
            c: self._dict_value_set(c)
            for c, reprs in by_column.items()
            if "codes" in reprs and c not in accs
        }
        values_dtypes = {
            c: self._values_dtype(c)
            for c, reprs in by_column.items()
            if "values" in reprs
        }

        pending: Dict[str, List[np.ndarray]] = {k: [] for k in keys}
        pending_rows = 0

        def drain(force_pad: bool):
            nonlocal pending, pending_rows
            while pending_rows >= batch_size or (
                force_pad and pending_rows > 0
            ):
                batch: Dict[str, np.ndarray] = {}
                width = min(pending_rows, batch_size)
                pad = batch_size - width
                for k in keys:
                    joined = (
                        np.concatenate(pending[k])
                        if len(pending[k]) > 1
                        else pending[k][0]
                    )
                    head, tail = joined[:width], joined[width:]
                    pending[k] = [tail] if len(tail) else []
                    if pad:
                        head = np.concatenate(
                            [head, np.zeros((pad,), dtype=head.dtype)]
                        )
                    batch[k] = head
                row_mask = np.ones((batch_size,), dtype=bool)
                if pad:
                    row_mask[width:] = False
                    for k in keys:
                        if k.endswith("::mask"):
                            batch[k] = batch[k] & row_mask
                batch[ROW_MASK] = row_mask
                # attach pending dictionary deltas to the FIRST batch
                # drained since the accumulator grew: every code in
                # this (and any earlier) batch indexes within the
                # shipped rows by construction
                for c in delta_cols:
                    acc = accs[c]
                    if acc.n > shipped_n[c]:
                        batch[DICT_DELTA_PREFIX + c] = {
                            "start": shipped_n[c],
                            "values": acc.slice_values(shipped_n[c]),
                        }
                        shipped_n[c] = acc.n
                pending_rows -= width
                yield batch

        scanner = self._source.scanner(
            columns=columns, batch_size=self._read_batch_rows
        )
        for record_batch in scanner.to_batches():
            if skip_rows > 0:
                if record_batch.num_rows <= skip_rows:
                    skip_rows -= record_batch.num_rows
                    continue
                record_batch = record_batch.slice(skip_rows)
                skip_rows = 0
            if record_batch.num_rows == 0:
                continue
            for ci, column_name in enumerate(columns):
                kind = self._schema.kind_of(column_name)
                wanted = by_column[column_name]
                col = record_batch.column(ci)
                if column_name in accs:
                    reprs = _column_batch_to_reprs(
                        col,
                        kind,
                        [r for r in wanted if r != "codes"],
                    )
                    # absorb new uniques + encode against the grown
                    # accumulator — the one traversal of the values
                    reprs["codes"] = accs[
                        column_name
                    ].absorb_and_encode(col)
                else:
                    reprs = _column_batch_to_reprs(
                        col,
                        kind,
                        wanted,
                        value_sets.get(column_name),
                        values_dtypes.get(column_name),
                    )
                for repr_name, arr in reprs.items():
                    pending[f"{column_name}::{repr_name}"].append(arr)
            pending_rows += record_batch.num_rows
            yield from drain(force_pad=False)
        yield from drain(force_pad=True)
        if start_batch == 0:
            # a full uninterrupted stream saw every record batch, so
            # the accumulator IS the global dictionary — cache it and a
            # later resident pass / profiler / single-analyzer consumer
            # pays no extra data pass
            for c in delta_cols:
                if (
                    c not in self._dictionaries
                    and accs[c].values is not None
                ):
                    self._store_dictionary(c, accs[c].values)

    # -- process-sharded ingest (ROADMAP item 3) -------------------------

    def shard_row_groups(
        self, process_index: int, process_count: int
    ) -> list:
        """Deterministic balanced row-group assignment: greedy
        least-loaded-by-rows over every (path-sorted) fragment's row
        groups. Every process computes the SAME full assignment from
        the same metadata, so the shards are a disjoint cover with no
        coordination. Returns this process's row-group fragments (in
        source order)."""
        if process_count <= 0:
            raise ValueError("process_count must be positive")
        if not 0 <= process_index < process_count:
            raise ValueError(
                f"process_index {process_index} outside "
                f"[0, {process_count})"
            )
        groups = []  # (rows, file_order, rg_order, fragment)
        fragments = sorted(
            self._source.get_fragments(), key=lambda f: f.path
        )
        for fi, fragment in enumerate(fragments):
            meta = fragment.metadata
            for gi, sub in enumerate(fragment.split_by_row_group()):
                groups.append(
                    (int(meta.row_group(gi).num_rows), fi, gi, sub)
                )
        loads = [0] * process_count
        assign: list = [[] for _ in range(process_count)]
        # largest-first greedy; ties broken by source order, target
        # ties by process index — fully deterministic
        for rows, fi, gi, sub in sorted(
            groups, key=lambda g: (-g[0], g[1], g[2])
        ):
            p = min(range(process_count), key=lambda i: (loads[i], i))
            loads[p] += rows
            assign[p].append((fi, gi, sub))
        return [
            sub for _, _, sub in sorted(assign[process_index])
        ]

    def shard_view(
        self, process_index: int, process_count: int
    ) -> "ParquetDataset":
        """This process's shard as a full ParquetDataset: a pyarrow
        FileSystemDataset restricted to the assigned row-group
        fragments (reads touch ONLY those row groups), fingerprint
        tagged with (process_index, process_count) so shard checkpoints
        never collide with whole-source ones."""
        fragments = self.shard_row_groups(process_index, process_count)
        view = ParquetDataset(
            pads.FileSystemDataset(
                fragments,
                self._source.schema,
                self._source.format,
                self._source.filesystem,
            ),
            self._read_batch_rows,
        )
        view._shard_tag = (int(process_index), int(process_count))
        # count_rows() on a row-group-restricted fragment reports the
        # WHOLE file (pyarrow quirk; scans are correctly restricted) —
        # recount from the assigned row-group metadata
        view._num_rows = sum(
            int(rg.num_rows)
            for fragment in fragments
            for rg in fragment.row_groups
        )
        return view

    # -- r10 ordered-pool work items -------------------------------------

    def ingest_work_items(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: Optional[int] = None,
        start_batch: int = 0,
    ):
        """Work-item twin of ``device_batches`` for the ordered ingest
        pool (engine/ingest.py). The READER (this generator) does only
        Arrow-level slicing to engine-batch granularity — zero-copy,
        and parquet decompression is already parallel inside the
        pyarrow scanner. Each item's heavy conversion runs on a pool
        WORKER via ``item.decode()`` (numpy reprs + per-batch uniques
        for delta columns — order-free work), and ``item.commit``
        runs strictly in batch order on the consumer (accumulator
        absorb, codes, delta cut, end-of-stream dictionary caching —
        all the order-dependent machinery).

        ``device_batches`` is deliberately untouched: workers=1 runs
        it, byte for byte the pre-r10 single-thread path — the
        differential oracle the pool tests pin against."""
        n = self.num_rows
        if batch_size is None:
            batch_size = n if n > 0 else 1
        batch_size = max(1, batch_size)
        skip_rows = start_batch * batch_size

        keys = self._dedup_requests(requests)
        by_column: Dict[str, List[str]] = {}
        for r in keys.values():
            by_column.setdefault(r.column, []).append(r.repr)
        columns = sorted(by_column)
        if not columns or n == 0:
            index = start_batch
            for batch in self._empty_or_counting_batches(
                keys, batch_size, n, skip_rows
            ):
                yield _PrecomputedIngestItem(index, batch)
                index += 1
            return
        delta_cols = sorted(
            c
            for c, reprs in by_column.items()
            if "codes" in reprs and self._dict_delta_mode(c)
        )
        state = _IngestPlanState(
            dataset=self,
            columns=columns,
            by_column=by_column,
            kinds={c: self._schema.kind_of(c) for c in columns},
            delta_cols=delta_cols,
            accs={
                c: self._delta_dicts.setdefault(c, _IncrementalDict())
                for c in delta_cols
            },
            shipped_n={c: 0 for c in delta_cols},
            value_sets={
                c: self._dict_value_set(c)
                for c, reprs in by_column.items()
                if "codes" in reprs and c not in delta_cols
            },
            values_dtypes={
                c: self._values_dtype(c)
                for c, reprs in by_column.items()
                if "values" in reprs
            },
            start_batch=start_batch,
            batch_size=batch_size,
        )

        pending: Dict[str, List[pa.Array]] = {c: [] for c in columns}
        pending_rows = 0
        index = start_batch
        # one-item holdback so the LAST item can carry final=True (it
        # owns the end-of-stream dictionary caching in commit)
        held: Optional[_ParquetIngestItem] = None

        def cut(force_pad: bool):
            nonlocal pending_rows, index, held
            while pending_rows >= batch_size or (
                force_pad and pending_rows > 0
            ):
                width = min(pending_rows, batch_size)
                chunks: Dict[str, List[pa.Array]] = {}
                for c in columns:
                    taken: List[pa.Array] = []
                    rest: List[pa.Array] = []
                    got = 0
                    for arr in pending[c]:
                        if got >= width:
                            rest.append(arr)
                            continue
                        take = min(len(arr), width - got)
                        taken.append(
                            arr if take == len(arr) else arr.slice(0, take)
                        )
                        if take < len(arr):
                            rest.append(arr.slice(take))
                        got += take
                    chunks[c] = taken
                    pending[c] = rest
                pending_rows -= width
                item = _ParquetIngestItem(index, width, state, chunks)
                index += 1
                if held is not None:
                    yield held
                held = item

        scanner = self._source.scanner(
            columns=columns, batch_size=self._read_batch_rows
        )
        for record_batch in scanner.to_batches():
            if skip_rows > 0:
                if record_batch.num_rows <= skip_rows:
                    skip_rows -= record_batch.num_rows
                    continue
                record_batch = record_batch.slice(skip_rows)
                skip_rows = 0
            if record_batch.num_rows == 0:
                continue
            for ci, column_name in enumerate(columns):
                pending[column_name].append(record_batch.column(ci))
            pending_rows += record_batch.num_rows
            yield from cut(force_pad=False)
        yield from cut(force_pad=True)
        if held is not None:
            held.final = True
            yield held

    def _empty_or_counting_batches(
        self, keys, batch_size: int, n: int, skip_rows: int = 0
    ):
        """No requested columns (Size()-only) or an empty source."""
        if n == 0:
            if skip_rows > 0:
                return
            batch: Dict[str, np.ndarray] = {}
            for k, r in keys.items():
                if r.repr == "codes" and self._dict_delta_mode(r.column):
                    # delta-mode codes: canonical int32, no pre-pass
                    batch[k] = np.zeros((batch_size,), dtype=np.int32)
                    continue
                kind = self._schema.kind_of(r.column)
                value_set = (
                    self._dict_value_set(r.column)
                    if r.repr == "codes"
                    else None
                )
                empty = _column_batch_to_reprs(
                    pa.array([], self._source.schema.field(r.column).type),
                    kind,
                    [r.repr],
                    value_set,
                    self._values_dtype(r.column)
                    if r.repr == "values"
                    else None,
                )[r.repr]
                batch[k] = np.zeros((batch_size,), dtype=empty.dtype)
            batch[ROW_MASK] = np.zeros((batch_size,), dtype=bool)
            yield batch
            return
        remaining = n - skip_rows
        while remaining > 0:
            width = min(remaining, batch_size)
            row_mask = np.zeros((batch_size,), dtype=bool)
            row_mask[:width] = True
            yield {ROW_MASK: row_mask}
            remaining -= width


class _IngestPlanState:
    """Shared, consumer-owned state of one ingest_work_items call: the
    dictionary accumulators and delta cursors every item's ordered
    ``commit`` mutates (only the pool consumer touches them, strictly
    in batch order), plus the immutable per-call conversion config."""

    __slots__ = (
        "dataset",
        "columns",
        "by_column",
        "kinds",
        "delta_cols",
        "accs",
        "shipped_n",
        "value_sets",
        "values_dtypes",
        "start_batch",
        "batch_size",
    )

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw[k])


class _ParquetIngestItem:
    """One engine batch's ingest work, split across pool stages:

    - ``decode()`` (any WORKER thread, order-free): cast/concat the
      Arrow chunk slices, run the authoritative repr conversion
      (_column_batch_to_reprs), pad to batch width, build the row
      mask; for delta columns also compute the batch's uniques
      (pc.unique — chunking-independent) but do NOT touch the shared
      accumulator.
    - ``commit(decoded)`` (the CONSUMER, strictly in batch order):
      absorb uniques into the shared _IncrementalDict, compute codes
      against the grown accumulator, cut the {start, values} delta
      payload, and — on the final item of an unresumed stream — cache
      the completed dictionary, exactly like device_batches' tail.

    ``complete`` is True when the item needs no ordered commit work
    (no delta columns), letting the pool wire-pack it on the worker.
    """

    __slots__ = (
        "index",
        "width",
        "final",
        "_state",
        "_chunks",
        "_delta_raw",
    )

    def __init__(self, index, width, state, chunks):
        self.index = index
        self.width = width
        self.final = False
        self._state = state
        self._chunks = chunks
        self._delta_raw = None

    @property
    def complete(self) -> bool:
        return not self._state.delta_cols

    def decode(self) -> Dict[str, np.ndarray]:
        st = self._state
        bs = st.batch_size
        delta_raw: Dict[str, tuple] = {}
        batch: Dict[str, np.ndarray] = {}
        for c in st.columns:
            chunks = []
            for arr in self._chunks[c]:
                if pa.types.is_dictionary(arr.type):
                    # cast per chunk: different record batches may
                    # carry different local dictionaries, which
                    # concat_arrays will not unify
                    arr = pc.cast(arr, arr.type.value_type)
                chunks.append(arr)
            col = (
                chunks[0]
                if len(chunks) == 1
                else pa.concat_arrays(chunks)
            )
            kind = st.kinds[c]
            wanted = st.by_column[c]
            if c in st.accs:
                reprs = _column_batch_to_reprs(
                    col, kind, [r for r in wanted if r != "codes"]
                )
                delta_raw[c] = (col, pc.drop_null(pc.unique(col)))
            else:
                reprs = _column_batch_to_reprs(
                    col,
                    kind,
                    wanted,
                    st.value_sets.get(c),
                    st.values_dtypes.get(c),
                )
            for repr_name, arr in reprs.items():
                batch[f"{c}::{repr_name}"] = arr
        pad = bs - self.width
        row_mask = np.ones((bs,), dtype=bool)
        if pad:
            row_mask[self.width:] = False
            for k, v in list(batch.items()):
                v = np.concatenate(
                    [v, np.zeros((pad,), dtype=v.dtype)]
                )
                if k.endswith("::mask"):
                    v = v & row_mask
                batch[k] = v
        batch[ROW_MASK] = row_mask
        self._delta_raw = delta_raw
        return batch

    def commit(
        self, decoded: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        st = self._state
        for c, (col, uniques) in (self._delta_raw or {}).items():
            acc = st.accs[c]
            acc.absorb(uniques)
            codes = acc.encode(col)
            pad = st.batch_size - self.width
            if pad:
                codes = np.concatenate(
                    [codes, np.zeros((pad,), dtype=codes.dtype)]
                )
            decoded[f"{c}::codes"] = np.ascontiguousarray(codes)
            if acc.n > st.shipped_n[c]:
                decoded[DICT_DELTA_PREFIX + c] = {
                    "start": st.shipped_n[c],
                    "values": acc.slice_values(st.shipped_n[c]),
                }
                st.shipped_n[c] = acc.n
        # drop the Arrow references: once committed the batch is pure
        # numpy and the column buffers can be reclaimed
        self._delta_raw = None
        self._chunks = None
        if self.final and st.start_batch == 0:
            ds = st.dataset
            for c in st.delta_cols:
                if (
                    c not in ds._dictionaries
                    and st.accs[c].values is not None
                ):
                    ds._store_dictionary(c, st.accs[c].values)
        return decoded


class _PrecomputedIngestItem:
    """Degenerate-path item (no requested columns, or an empty
    source): the batch is already built on the reader; decode/commit
    are identity."""

    __slots__ = ("index", "width", "final", "_batch")
    complete = True

    def __init__(self, index, batch):
        self.index = index
        self.width = None
        self.final = False
        self._batch = batch

    def decode(self):
        return self._batch

    def commit(self, decoded):
        return decoded
