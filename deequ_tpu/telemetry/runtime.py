"""Telemetry runtime: ONE object owning the tracer, the metrics
registry, the listener set, and structured export.

Layers and their gating:

- **counters/gauges/histograms** (metrics.py) — always on; a bump per
  pass/batch event costs what the seed's ad-hoc globals already cost.
- **spans, engine events, run captures, listeners, JSONL** — gated by
  ``enabled`` (default on; ``DEEQU_TPU_TELEMETRY=0`` or
  ``configure(enabled=False)`` turns them into shared no-ops with no
  measurable cost to a scan).
- **JSONL event log** — off until a path is configured
  (``configure(jsonl_path=...)`` or ``DEEQU_TPU_TELEMETRY_JSONL``);
  every finished span, engine event, and run summary appends one line.

A *run capture* scopes spans/events/pass records to one logical run
(one ``AnalysisRunner.do_analysis_run``); its ``summary()`` is the dict
attached to ``AnalyzerContext``/``VerificationResult`` and is what the
repository persists as operational records (oprecords.py).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from deequ_tpu.telemetry.listeners import RunListener
from deequ_tpu.telemetry.metrics import MetricsRegistry
from deequ_tpu.telemetry.spans import (
    NOOP_SPAN,
    NOOP_SPAN_CM,
    Span,
    TraceContext,
    Tracer,
    clock,
    epoch,
    next_span_id,
)

_NOOP_SCOPE = contextlib.nullcontext(None)

_run_ids = itertools.count(1)
_UNSET = object()


class RunCapture:
    """Spans/events/pass records of one logical run, plus the counter
    snapshot taken at run start so the summary reports DELTAS."""

    def __init__(self, run_id: int, name: str, counters_before: Dict[str, int]):
        self.run_id = run_id
        self.name = name
        self.counters_before = counters_before
        self.spans: List[Dict[str, Any]] = []
        self.events: List[Dict[str, Any]] = []
        self.passes: List[Dict[str, Any]] = []
        self.wall_s = 0.0
        # the summary computed when the run context exits (None while
        # the run is still open) — what callers attach to results
        self.final: Optional[Dict[str, Any]] = None

    def summary(self, counters_now: Dict[str, int]) -> Dict[str, Any]:
        before = self.counters_before
        counters = {
            k: v - before.get(k, 0)
            for k, v in counters_now.items()
            if v - before.get(k, 0) != 0
        }
        return {
            "run_id": self.run_id,
            "name": self.name,
            "wall_s": self.wall_s,
            "passes": list(self.passes),
            "events": list(self.events),
            "spans": list(self.spans),
            "counters": counters,
        }


class _NoopCapture:
    """Stand-in when telemetry is disabled: absorbs nothing, summarizes
    to None (callers then skip metadata/summary attachment)."""

    run_id = 0
    name = ""
    spans: List = []
    events: List = []
    passes: List = []
    wall_s = 0.0
    final = None

    def summary(self, counters_now=None):  # noqa: ARG002
        return None


NOOP_CAPTURE = _NoopCapture()


class Telemetry:
    """The unified telemetry runtime. A process-default instance is
    reachable via :func:`get_telemetry`; tests may instantiate their own
    for isolation."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        jsonl_path: Optional[str] = None,
        annotate: bool = True,
    ):
        if enabled is None:
            enabled = os.environ.get(
                "DEEQU_TPU_TELEMETRY", "1"
            ).lower() not in ("0", "false", "off")
        if jsonl_path is None:
            jsonl_path = os.environ.get("DEEQU_TPU_TELEMETRY_JSONL") or None
        self.enabled = bool(enabled)
        self.metrics = MetricsRegistry()
        self.tracer = Tracer(annotate=annotate)
        # fleet-timeline tag for every span record this process emits
        # (set per-host in the distributed service, per-child in spawn
        # children); empty = untagged
        self.process_label = os.environ.get(
            "DEEQU_TPU_TELEMETRY_PROCESS", ""
        )
        # best-effort callbacks fed every finished span RECORD (the
        # spawn boundary streams child spans to the parent through one)
        self._span_sinks: List[Any] = []
        self._listeners: List[RunListener] = []
        self._local = threading.local()
        self._jsonl_path = jsonl_path
        self._jsonl_lock = threading.Lock()
        # global ring of recent span records/events (debugging aid when
        # no capture is active); bounded so long processes never grow
        self._recent: deque = deque(maxlen=4096)
        self._recent_lock = threading.Lock()

    # -- configuration --------------------------------------------------

    def configure(
        self,
        enabled: Optional[bool] = None,
        jsonl_path: Any = _UNSET,
        annotate: Optional[bool] = None,
        process: Optional[str] = None,
    ) -> "Telemetry":
        if enabled is not None:
            self.enabled = bool(enabled)
        if jsonl_path is not _UNSET:
            self._jsonl_path = jsonl_path
        if annotate is not None:
            self.tracer.annotate = bool(annotate)
        if process is not None:
            self.process_label = process
        return self

    @property
    def jsonl_path(self) -> Optional[str]:
        return self._jsonl_path

    # -- listeners ------------------------------------------------------

    def add_listener(self, listener: RunListener) -> RunListener:
        self._listeners.append(listener)
        return listener

    def remove_listener(self, listener: RunListener) -> None:
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    @property
    def listeners(self) -> List[RunListener]:
        return list(self._listeners)

    def _dispatch(self, method: str, *args: Any) -> None:
        for listener in self._listeners:
            try:
                getattr(listener, method)(*args)
            except Exception:  # noqa: BLE001 — a broken listener must
                # never fail a run; the counter keeps it from being
                # silent
                self.metrics.counter("telemetry.listener_errors").inc()

    # -- counters passthrough ------------------------------------------

    def counter(self, name: str):
        return self.metrics.counter(name)

    # -- captures -------------------------------------------------------

    def _captures(self) -> List[RunCapture]:
        stack = getattr(self._local, "captures", None)
        if stack is None:
            stack = []
            self._local.captures = stack
        return stack

    @contextlib.contextmanager
    def run(self, name: str = "run") -> Iterator[RunCapture]:
        """Open a run capture: spans/events/pass records finished on
        this thread while the context is live are scoped to it."""
        if not self.enabled:
            yield NOOP_CAPTURE
            return
        cap = RunCapture(
            next(_run_ids), name, self.metrics.counters_snapshot()
        )
        self._dispatch("on_run_start", cap.run_id, name)
        stack = self._captures()
        stack.append(cap)
        t0 = clock()
        try:
            with self.tracer.span(
                f"run:{name}", on_finish=self._on_span_finish, run=name
            ):
                yield cap
        finally:
            cap.wall_s = clock() - t0
            if cap in stack:
                stack.remove(cap)
            summary = cap.summary(self.metrics.counters_snapshot())
            cap.final = summary
            self._write_jsonl(
                {"type": "run_summary", **_summary_sans_spans(summary)}
            )
            self._dispatch("on_run_end", cap.run_id, name, summary)

    # -- spans ----------------------------------------------------------

    def span(self, name: str, **attributes: Any):
        """A nested span (see spans.Tracer); shared no-op when
        disabled."""
        if not self.enabled:
            return NOOP_SPAN_CM
        return self.tracer.span(
            name, on_finish=self._on_span_finish, **attributes
        )

    def _on_span_finish(self, sp: Span) -> None:
        self._ingest_record(sp.as_record())

    def _ingest_record(self, record: Dict[str, Any]) -> None:
        """Route one finished span RECORD to captures, the recent ring,
        span sinks, and the JSONL log — live and replayed spans share
        this path."""
        if self.process_label and not record.get("process"):
            record["process"] = self.process_label
        captures = self._captures()
        if captures:
            record["run_id"] = captures[-1].run_id
            for cap in captures:
                cap.spans.append(record)
        with self._recent_lock:
            self._recent.append(record)
        for sink in self._span_sinks:
            try:
                sink(record)
            except Exception:  # noqa: BLE001 — a broken sink must never
                # fail a run (same contract as listeners)
                self.metrics.counter("telemetry.listener_errors").inc()
        self._write_jsonl(record)

    # -- trace propagation ----------------------------------------------

    def trace_scope(self, ctx: Optional[TraceContext]):
        """Make ``ctx`` the ambient trace on this thread (no-op when
        telemetry is disabled or ``ctx`` is None — the zero-cost-off
        path allocates nothing)."""
        if not self.enabled or ctx is None:
            return _NOOP_SCOPE
        return self.tracer.trace_scope(ctx)

    def current_trace(self) -> Optional[TraceContext]:
        if not self.enabled:
            return None
        return self.tracer.current_trace()

    def add_span_sink(self, sink: Any) -> Any:
        self._span_sinks.append(sink)
        return sink

    def remove_span_sink(self, sink: Any) -> None:
        try:
            self._span_sinks.remove(sink)
        except ValueError:
            pass

    def emit_span(
        self,
        name: str,
        wall_s: float = 0.0,
        *,
        trace: Optional[TraceContext] = None,
        span_id: Optional[int] = None,
        parent_id: Optional[int] = None,
        started_at: Optional[float] = None,
        **attributes: Any,
    ) -> Optional[Dict[str, Any]]:
        """Record a span that was MEASURED rather than lived-through: a
        queue wait read off ticket timestamps, a lease wait, a phase
        bucket. Parent resolution: explicit ``parent_id`` > current
        open span on this thread > ambient trace root > None. Pass
        ``span_id=trace.span_id`` (with ``parent_id=None``) to emit the
        trace's reserved root."""
        if not self.enabled:
            return None
        ctx = trace if trace is not None else self.tracer.current_trace()
        sid = span_id if span_id is not None else next_span_id()
        if parent_id is None and span_id is None:
            current = self.tracer.current()
            if current is not None:
                parent_id = current.span_id
            elif ctx is not None:
                parent_id = ctx.span_id
        sp = Span(
            name=name,
            span_id=sid,
            parent_id=parent_id,
            thread=threading.current_thread().name,
            started_at=(
                started_at if started_at is not None
                else epoch() - max(0.0, wall_s)
            ),
            wall_s=max(0.0, float(wall_s)),
            attributes=dict(attributes),
            trace_id=ctx.trace_id if ctx is not None else None,
            process=ctx.process if ctx is not None else "",
        )
        record = sp.as_record()
        self._ingest_record(record)
        return record

    def replay_spans(
        self,
        records: List[Dict[str, Any]],
        *,
        root_parent_id: Optional[int] = None,
        trace_id: Optional[str] = None,
        process: str = "",
    ) -> List[Dict[str, Any]]:
        """Re-ingest span records produced by ANOTHER process (a spawn
        child): span ids are remapped onto this process's counter so
        they cannot collide, internal parentage is preserved, and any
        record whose parent is unknown re-roots under
        ``root_parent_id``. Returns the re-ingested records."""
        if not self.enabled or not records:
            return []
        id_map = {
            r["span_id"]: next_span_id()
            for r in records
            if isinstance(r.get("span_id"), int)
        }
        out: List[Dict[str, Any]] = []
        for r in records:
            if not isinstance(r, dict) or r.get("type") != "span":
                continue
            rec = dict(r)
            rec["span_id"] = id_map.get(rec.get("span_id"), next_span_id())
            parent = rec.get("parent_id")
            # the anchor check comes FIRST: the child's local id counter
            # can collide with the shipped parent id, and a span that
            # parents to the anchor must stay on it, not follow the
            # colliding child id through the remap
            if parent == root_parent_id and parent is not None:
                pass  # already anchored on the shipped parent span
            elif parent in id_map:
                rec["parent_id"] = id_map[parent]
            else:
                rec["parent_id"] = root_parent_id
            if trace_id is not None:
                rec["trace_id"] = trace_id
            if process and not rec.get("process"):
                rec["process"] = process
            rec.pop("run_id", None)  # re-attributed by _ingest_record
            self._ingest_record(rec)
            out.append(rec)
        return out

    @contextlib.contextmanager
    def pass_span(
        self, name: str, rows: int = 0, num_analyzers: int = 0
    ) -> Iterator[Any]:
        """An engine pass: a span named ``pass:<name>`` plus the
        on_pass_start/end listener callbacks and a per-run pass record.
        Always measures wall (two clock calls per PASS — nothing per
        batch) so the RunMetadata compatibility shim keeps working even
        when span capture is off."""
        if not self.enabled:
            t0 = clock()
            sp = Span(name=f"pass:{name}", span_id=0, parent_id=None,
                      thread="", started_at=0.0)
            try:
                yield sp
            finally:
                sp.wall_s = clock() - t0
            return
        self._dispatch("on_pass_start", name, rows, num_analyzers)
        sp_out = None
        try:
            with self.tracer.span(
                f"pass:{name}",
                on_finish=self._on_span_finish,
                rows=rows,
                num_analyzers=num_analyzers,
            ) as sp:
                sp_out = sp
                yield sp
        finally:
            if sp_out is not None:
                record = {
                    "pass": name,
                    "wall_s": sp_out.wall_s,
                    "rows": rows,
                    "num_analyzers": num_analyzers,
                }
                for cap in self._captures():
                    cap.passes.append(record)
                self.metrics.histogram("pass.wall_s").observe(
                    sp_out.wall_s
                )
                self._dispatch(
                    "on_pass_end", name, sp_out.wall_s, rows, num_analyzers
                )

    # -- engine events --------------------------------------------------

    def event(self, name: str, **fields: Any) -> Dict[str, Any]:
        """A structured engine event ({"event": name, **fields}):
        captured per-run, JSONL-logged, and fanned out to
        ``on_engine_event`` listeners."""
        record = {"event": name, **fields}
        if not self.enabled:
            return record
        captures = self._captures()
        for cap in captures:
            cap.events.append(record)
        with self._recent_lock:
            self._recent.append({"type": "event", **record})
        self._write_jsonl(
            {
                "type": "event",
                "run_id": captures[-1].run_id if captures else None,
                **record,
            }
        )
        self._dispatch("on_engine_event", record)
        return record

    def analyzer_computed(self, analyzer: Any, metric: Any) -> None:
        """Fan an (analyzer, metric) result out to listeners."""
        if self.enabled:
            self._dispatch("on_analyzer_computed", analyzer, metric)

    def check_evaluated(self, check: Any, result: Any) -> None:
        """Fan an evaluated check out to listeners."""
        if self.enabled:
            self._dispatch("on_check_evaluated", check, result)

    def recent(self) -> List[Dict[str, Any]]:
        with self._recent_lock:
            return list(self._recent)

    # -- export ---------------------------------------------------------

    def _write_jsonl(self, record: Dict[str, Any]) -> None:
        path = self._jsonl_path
        if not path:
            return
        try:
            line = json.dumps(record, default=str)
        except TypeError:
            line = json.dumps({"type": "unserializable", "repr": repr(record)})
        with self._jsonl_lock:
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")


def _summary_sans_spans(summary: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(summary)
    out.pop("spans", None)
    return out


_default = Telemetry()
_default_lock = threading.Lock()


def get_telemetry() -> Telemetry:
    """The process-default Telemetry instance."""
    return _default


def configure(
    enabled: Optional[bool] = None,
    jsonl_path: Any = _UNSET,
    annotate: Optional[bool] = None,
    process: Optional[str] = None,
) -> Telemetry:
    """Configure the process-default instance (see
    ``Telemetry.configure``)."""
    with _default_lock:
        return _default.configure(
            enabled=enabled,
            jsonl_path=jsonl_path,
            annotate=annotate,
            process=process,
        )
