"""Sync discipline (ISSUE 6 satellite): the engine's host<->device
contract, pinned with telemetry counters.

The tunneled-TPU cost model makes every host<->device round trip a
5-10 ms tax, so the engine's whole design funnels synchronization into
ONE place: the packed epilogue fetch (engine/pack.py
``packed_device_get``). These tests pin the measured counter deltas —
a full ColumnProfiler run pays exactly 1 data pass + 1 device fetch
(2 of each when a string column numeric-promotes, the one legitimate
second pass), and a multi-batch streaming KLL run still fetches ONCE
at the end, never per step. A regression here (a stray
``device_get`` in a hot loop, a second accidental traversal) shows up
as a counter bump long before anyone notices seconds on a dashboard.

The static half of the same contract is tools/telemetry_lint.py:
``device_get``/``asarray`` NAME tokens inside ``deequ_tpu/engine/``
outside pack.py need a same-line ``# sync-ok:`` waiver. The last test
runs the lint over the repo so a new unwaived sync fails CI, not
production.
"""

import os

import numpy as np

from deequ_tpu import config
from deequ_tpu.analyzers import AnalysisRunner, ApproxQuantile, Mean
from deequ_tpu.data import Dataset
from deequ_tpu.profiles.profiler import ColumnProfiler
from deequ_tpu.telemetry import get_telemetry

COUNTERS = (
    "engine.scans",
    "engine.data_passes",
    "engine.device_fetches",
    "engine.fetch_bytes",
)


def _deltas(fn):
    """Run ``fn`` and return the engine counter deltas it caused."""
    tm = get_telemetry()
    before = tm.metrics.counters_snapshot()
    fn()
    after = tm.metrics.counters_snapshot()
    return {k: after.get(k, 0) - before.get(k, 0) for k in COUNTERS}


def _mixed_profile_data(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset.from_pydict(
        {
            "price": rng.normal(size=n).astype(np.float32),
            "qty": rng.integers(0, 100, n),
            "cat": np.array(["red", "green", "blue"])[
                rng.integers(0, 3, n)
            ],
        }
    )


class TestProfileSyncBudget:
    def test_mixed_profile_is_one_pass_one_fetch(self):
        """The common case: numeric + low-cardinality string columns.
        Speculative pass-1 histograms (engine/scan.py) mean NO second
        pass, and the packed epilogue means ONE fetch for the whole
        ~15-analyzer plan."""
        ds = _mixed_profile_data()
        d = _deltas(lambda: ColumnProfiler.profile(ds))
        assert d["engine.scans"] == 1, d
        assert d["engine.data_passes"] == 1, d
        assert d["engine.device_fetches"] == 1, d
        # the fetch actually moved the packed state (bytes attributed)
        assert d["engine.fetch_bytes"] > 0, d

    def test_promoted_string_profile_is_two_passes_two_fetches(self):
        """The one SANCTIONED second pass: a string column whose values
        all parse numeric promotes after pass 1, and the numeric
        analyzers re-scan. Exactly 2 passes / 2 fetches — not 3, and
        never per-column."""
        rng = np.random.default_rng(1)
        ds = Dataset.from_pydict(
            {
                "x": rng.normal(size=20_000).astype(np.float32),
                "as_text": [
                    f"{v:.3f}" for v in rng.normal(size=20_000)
                ],
            }
        )
        d = _deltas(lambda: ColumnProfiler.profile(ds))
        assert d["engine.scans"] == 2, d
        assert d["engine.data_passes"] == 2, d
        assert d["engine.device_fetches"] == 2, d


class TestStreamingSyncBudget:
    def test_multibatch_kll_run_fetches_once(self):
        """8 streaming batches through the KLL unit: the per-step
        sample fetch is folded into the scan's single packed epilogue
        (ISSUE 6 tentpole a) — the step loop itself never calls
        ``device_get``."""
        rng = np.random.default_rng(2)
        ds = Dataset.from_pydict(
            {
                "a": rng.normal(size=4096).astype(np.float32),
                "b": rng.normal(size=4096).astype(np.float32),
            }
        )
        analyzers = [
            ApproxQuantile("a", 0.5),
            ApproxQuantile("b", 0.5),
            Mean("a"),
        ]

        def run():
            with config.configure(batch_size=512, device_cache_bytes=0):
                ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
            for a in analyzers:
                assert ctx.metric(a).value.is_success

        d = _deltas(run)
        assert d["engine.scans"] == 1, d
        assert d["engine.data_passes"] == 1, d
        assert d["engine.device_fetches"] == 1, d


class TestSyncLint:
    def test_engine_hot_paths_are_lint_clean(self):
        """The static rule behind the counters: no unwaived
        ``device_get``/``asarray`` token inside deequ_tpu/engine/
        outside the packed epilogue (tools/telemetry_lint.py)."""
        from tools.telemetry_lint import find_violations

        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        assert find_violations(root) == []
