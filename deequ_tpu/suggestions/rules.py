"""Constraint suggestion rules.

Reference: ``src/main/scala/com/amazon/deequ/suggestions/rules/``
(SURVEY.md §2.5): each ``ConstraintRule[ColumnProfile]`` decides
``shouldBeApplied(profile, numRecords)`` and produces a candidate
carrying a description, a ready-to-paste code snippet, and the actual
Constraint. ``DEFAULT_RULES`` mirrors the reference's ``Rules.DEFAULT``.
Code snippets are Python (this framework's DSL), not Scala.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, List, Optional

from deequ_tpu.checks.check import Check, CheckLevel, ConstrainableDataTypes
from deequ_tpu.data.table import Kind
from deequ_tpu.profiles.profiler import (
    NumericColumnProfile,
    StandardColumnProfile,
)


@dataclass
class ConstraintSuggestion:
    constraint_description: str
    column_name: str
    current_value: str
    description: str
    suggesting_rule: str
    code_for_constraint: str
    # applying the suggestion to a Check (used by train/test evaluation)
    apply_to_check: Callable[[Check], Check]


class ConstraintRule:
    """shouldBeApplied + candidate (reference: ConstraintRule)."""

    @property
    def rule_description(self) -> str:
        raise NotImplementedError

    def should_be_applied(
        self, profile: StandardColumnProfile, num_records: int
    ) -> bool:
        raise NotImplementedError

    def candidate(
        self, profile: StandardColumnProfile, num_records: int
    ) -> ConstraintSuggestion:
        raise NotImplementedError


class CompleteIfCompleteRule(ConstraintRule):
    """Column has no nulls -> suggest is_complete."""

    rule_description = (
        "If a column is complete in the sample, we suggest a NOT NULL "
        "constraint"
    )

    def should_be_applied(self, profile, num_records):
        return profile.completeness == 1.0

    def candidate(self, profile, num_records):
        column = profile.column
        return ConstraintSuggestion(
            constraint_description=f"'{column}' is not null",
            column_name=column,
            current_value="Completeness: 1.0",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=f'.is_complete("{column}")',
            apply_to_check=lambda check: check.is_complete(column),
        )


class RetainCompletenessRule(ConstraintRule):
    """Partially complete column -> keep completeness above the lower
    bound of its binomial confidence interval."""

    rule_description = (
        "If a column is incomplete in the sample, we model its "
        "completeness as a binomial variable and require the estimate "
        "to stay above the interval's lower bound"
    )

    def __init__(self, min_completeness: float = 0.2, max_completeness: float = 1.0):
        self.min_completeness = min_completeness
        self.max_completeness = max_completeness

    def should_be_applied(self, profile, num_records):
        return (
            self.min_completeness <= profile.completeness
            < self.max_completeness
        )

    def candidate(self, profile, num_records):
        column = profile.column
        p = profile.completeness
        n = max(num_records, 1)
        interval = 1.96 * math.sqrt(p * (1 - p) / n)
        bound = round(max(0.0, p - interval), 2)
        return ConstraintSuggestion(
            constraint_description=(
                f"'{column}' has less than {round((1 - bound) * 100)}% "
                "missing values"
            ),
            column_name=column,
            current_value=f"Completeness: {p}",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=(
                f'.has_completeness("{column}", lambda c: c >= {bound})'
            ),
            apply_to_check=lambda check: check.has_completeness(
                column, lambda c: c >= bound
            ),
        )


class RetainTypeRule(ConstraintRule):
    """String column whose values all parse as a concrete type ->
    constrain the inferred type."""

    rule_description = (
        "If a string column's values parse as a single concrete type, "
        "we suggest a data-type constraint"
    )

    _KIND_TO_DT = {
        Kind.INTEGRAL: ConstrainableDataTypes.INTEGRAL,
        Kind.FRACTIONAL: ConstrainableDataTypes.FRACTIONAL,
        Kind.BOOLEAN: ConstrainableDataTypes.BOOLEAN,
    }

    def should_be_applied(self, profile, num_records):
        return (
            profile.is_data_type_inferred
            and profile.data_type in self._KIND_TO_DT
        )

    def candidate(self, profile, num_records):
        column = profile.column
        dt = self._KIND_TO_DT[profile.data_type]
        # Integral values also satisfy FRACTIONAL (ints embed in floats)
        assert_dt = (
            ConstrainableDataTypes.NUMERIC
            if dt in (ConstrainableDataTypes.INTEGRAL, ConstrainableDataTypes.FRACTIONAL)
            else dt
        )
        return ConstraintSuggestion(
            constraint_description=f"'{column}' has type {dt.value}",
            column_name=column,
            current_value=f"DataType: {profile.data_type.value}",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=(
                f'.has_data_type("{column}", '
                f"ConstrainableDataTypes.{dt.name})"
            ),
            apply_to_check=lambda check: check.has_data_type(
                column, assert_dt
            ),
        )


class CategoricalRangeRule(ConstraintRule):
    """Low-cardinality column -> values contained in the observed set."""

    rule_description = (
        "If a column has a small set of observed values, we suggest an "
        "IS IN (...) constraint over them"
    )

    def should_be_applied(self, profile, num_records):
        if profile.histogram is None:
            return False
        unique_ratio = profile.approximate_num_distinct_values / max(
            num_records, 1
        )
        return unique_ratio < 0.1

    def candidate(self, profile, num_records):
        column = profile.column
        hist = profile.histogram
        categories = [k for k in hist.values if k != "NullValue"]
        quoted = ", ".join(f'"{c}"' for c in sorted(categories))
        values = sorted(categories)
        return ConstraintSuggestion(
            constraint_description=(
                f"'{column}' has value range {quoted}"
            ),
            column_name=column,
            current_value=f"Distinct values: {len(categories)}",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=(
                f'.is_contained_in("{column}", [{quoted}])'
            ),
            apply_to_check=lambda check: check.is_contained_in(
                column, values
            ),
        )


class FractionalCategoricalRangeRule(ConstraintRule):
    """Most (default 90%) of the rows fall into a small category set."""

    rule_description = (
        "If most values fall into a small category set, we suggest an "
        "IS IN (...) constraint holding for that fraction of rows"
    )

    def __init__(self, target_data_coverage_fraction: float = 0.9):
        self.target = target_data_coverage_fraction

    def should_be_applied(self, profile, num_records):
        hist = profile.histogram
        if hist is None or num_records == 0:
            return False
        top = sorted(
            (dv.ratio for k, dv in hist.values.items() if k != "NullValue"),
            reverse=True,
        )
        covered = 0.0
        for i, r in enumerate(top):
            covered += r
            if covered >= self.target:
                return i + 1 < len(top)  # strictly smaller set than all
        return False

    def candidate(self, profile, num_records):
        column = profile.column
        hist = profile.histogram
        ranked = sorted(
            (
                (k, dv.ratio)
                for k, dv in hist.values.items()
                if k != "NullValue"
            ),
            key=lambda kv: -kv[1],
        )
        covered = 0.0
        keep: List[str] = []
        for k, r in ranked:
            keep.append(k)
            covered += r
            if covered >= self.target:
                break
        quoted = ", ".join(f'"{c}"' for c in keep)
        # assert at a slightly laxer bound than observed coverage
        bound = round(max(0.0, covered - 0.05), 2)
        values = list(keep)
        return ConstraintSuggestion(
            constraint_description=(
                f"'{column}' has value range {quoted} for at least "
                f"{round(bound * 100)}% of values"
            ),
            column_name=column,
            current_value=f"Coverage: {covered:.2f}",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=(
                f'.is_contained_in("{column}", [{quoted}], '
                f"lambda v: v >= {bound})"
            ),
            apply_to_check=lambda check: check.is_contained_in(
                column, values, lambda v: v >= bound
            ),
        )


class NonNegativeNumbersRule(ConstraintRule):
    """Numeric column with min >= 0 -> suggest non-negativity."""

    rule_description = (
        "If a numeric column's observed minimum is non-negative, we "
        "suggest a non-negativity constraint"
    )

    def should_be_applied(self, profile, num_records):
        return (
            isinstance(profile, NumericColumnProfile)
            and profile.minimum is not None
            and profile.minimum >= 0.0
        )

    def candidate(self, profile, num_records):
        column = profile.column
        return ConstraintSuggestion(
            constraint_description=f"'{column}' has no negative values",
            column_name=column,
            current_value=f"Minimum: {profile.minimum}",
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=f'.is_non_negative("{column}")',
            apply_to_check=lambda check: check.is_non_negative(column),
        )


class UniqueIfApproximatelyUniqueRule(ConstraintRule):
    """Approx distinct count ~ row count -> suggest uniqueness."""

    rule_description = (
        "If the approximate distinct count is within the sketch's error "
        "of the row count, we suggest a UNIQUE constraint"
    )

    def should_be_applied(self, profile, num_records):
        if num_records == 0 or profile.completeness < 1.0:
            return False
        uniqueness = profile.approximate_num_distinct_values / num_records
        return abs(1.0 - uniqueness) <= 0.08

    def candidate(self, profile, num_records):
        column = profile.column
        return ConstraintSuggestion(
            constraint_description=f"'{column}' is unique",
            column_name=column,
            current_value=(
                f"ApproxDistinctness: "
                f"{profile.approximate_num_distinct_values / max(num_records, 1)}"
            ),
            description=self.rule_description,
            suggesting_rule=type(self).__name__,
            code_for_constraint=f'.is_unique("{column}")',
            apply_to_check=lambda check: check.is_unique(column),
        )


DEFAULT_RULES: List[ConstraintRule] = [
    CompleteIfCompleteRule(),
    RetainCompletenessRule(),
    RetainTypeRule(),
    CategoricalRangeRule(),
    FractionalCategoricalRangeRule(),
    NonNegativeNumbersRule(),
    UniqueIfApproximatelyUniqueRule(),
]
