"""Memory-pressure classification and adaptive batch backoff.

The reference delegates out-of-memory survival to Spark's executor
re-scheduling (a task that OOMs is simply retried elsewhere); the
jax_graft engine drives its own scan loop on a fixed device, so this
module supplies the equivalent story (docs/RESILIENCE.md "Memory
pressure"):

- :func:`classify_memory_pressure` — the ONE place device allocation
  failures (XLA ``RESOURCE_EXHAUSTED`` / ``XlaRuntimeError`` OOM
  shapes) and host ``MemoryError`` are recognized and mapped onto
  :class:`MemoryPressureError`. Everything else in the engine matches
  against this classifier, never against exception strings — enforced
  by ``tools/telemetry_lint.py``.
- :class:`MemoryPressureError` — its own family, deliberately DISTINCT
  from the transient/deterministic taxonomy in ``engine/resilience.py``:
  retrying the same allocation at the same size re-OOMs (so it is not
  transient), but shrinking the allocation usually succeeds (so it is
  not a quarantine-worthy deterministic failure either). The scan loops
  answer it with :class:`AdaptiveBatchBackoff`; only an allocation that
  still fails at ``config.min_batch_rows`` flows into PR 3's
  quarantine -> ``ScanDegradation``.
- :class:`AdaptiveBatchBackoff` — the effective-batch-size state
  machine: geometric halving down to ``min_rows`` on OOM, optional
  heal-up (doubling) after ``heal_after`` consecutive clean batches.
  Observable via the ``engine.batch_rows_effective`` gauge and the
  ``engine.oom_events`` / ``engine.batch_size_backoffs`` counters plus
  ``scan_memory_pressure`` events (rendered by ``tools/obs_report.py``).
- :class:`SimulatedResourceExhausted` + :func:`simulated_device_oom` —
  the fault-injection surface (``testing/faults.py``): a synthetic
  exception carrying a real XLA-shaped ``RESOURCE_EXHAUSTED`` message,
  so tests exercise the same message-matching classification path a
  live device failure would take, with zero real allocation pressure.

Classification is intentionally conservative: message markers are only
consulted for exception types that plausibly come from the runtime
(``XlaRuntimeError``, ``RuntimeError``, the simulated stand-in) — a
``ValueError`` that merely MENTIONS memory never classifies.
"""

from __future__ import annotations

from typing import Any, Optional

from deequ_tpu.telemetry import get_telemetry


class MemoryPressureError(Exception):
    """A device or host allocation failure, classified. ``origin`` is
    ``"device"`` (XLA allocator) or ``"host"`` (Python ``MemoryError``).
    NOT transient (same-size retry re-OOMs) and not deterministic data
    corruption either — the scan loops shrink the batch instead."""

    def __init__(self, message: str, origin: str = "device"):
        super().__init__(message)
        self.origin = origin


class BackoffExhausted(MemoryPressureError):
    """Allocation still failed at ``min_rows`` — nothing left to
    shrink. The scan quarantines the remaining rows of the unit
    (PR 3's quarantine -> ScanDegradation path)."""


class SimulatedResourceExhausted(Exception):
    """Test-only stand-in for ``jaxlib``'s ``XlaRuntimeError`` OOM:
    same message shape, no real allocation. Raised by the fault
    harness (``testing/faults.py``) so classification is exercised
    end-to-end on CPU."""


def simulated_device_oom(rows: int = 0, where: str = "dispatch"):
    """An exception shaped like a real XLA device OOM (classified by
    message, exactly like the live error would be)."""
    return SimulatedResourceExhausted(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        f"{max(int(rows), 1) * 8} bytes (injected at {where})"
    )


# message markers a runtime allocation failure carries; matched ONLY
# for the runtime exception types below
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "Resource exhausted",
    "Failed to allocate",
)

# exception type NAMES eligible for message matching — jaxlib's
# XlaRuntimeError is matched by name so this module never imports
# jaxlib internals (and keeps working across jaxlib versions)
_RUNTIME_TYPE_NAMES = ("XlaRuntimeError", "RuntimeError")


def classify_memory_pressure(
    exc: BaseException,
) -> Optional[MemoryPressureError]:
    """``exc`` as a :class:`MemoryPressureError`, or None when it is
    not an allocation failure. The single classification point — no
    other engine module matches OOM strings (telemetry_lint rule)."""
    if isinstance(exc, MemoryPressureError):
        return exc
    if isinstance(exc, MemoryError):
        pressure = MemoryPressureError(
            f"host allocation failed: {exc}", origin="host"
        )
        pressure.__cause__ = exc
        return pressure
    if isinstance(exc, SimulatedResourceExhausted) or (
        type(exc).__name__ in _RUNTIME_TYPE_NAMES
    ):
        message = str(exc)
        if any(marker in message for marker in _OOM_MARKERS):
            pressure = MemoryPressureError(message, origin="device")
            pressure.__cause__ = exc
            return pressure
    return None


def record_memory_pressure(
    stage: str,
    batch_index: int,
    rows: int,
    pressure: MemoryPressureError,
) -> None:
    """Count + event one classified OOM (``engine.oom_events`` and a
    ``scan_memory_pressure`` event with ``action="oom"``)."""
    tm = get_telemetry()
    tm.counter("engine.oom_events").inc()
    tm.event(
        "scan_memory_pressure",
        action="oom",
        stage=stage,
        batch_index=int(batch_index),
        rows=int(rows),
        origin=pressure.origin,
        error=str(pressure)[:200],
    )


def record_spill_downgrade(stage: str, columns, path: str) -> None:
    """Count + event one memory-pressure downgrade of a spill/collector
    finalize (``engine.spill_downgrades``; the downgrade chain is
    collector -> deferred per-plan re-scan -> host Arrow)."""
    tm = get_telemetry()
    tm.counter("engine.spill_downgrades").inc()
    tm.event(
        "scan_memory_pressure",
        action="spill-downgrade",
        stage=stage,
        columns=list(columns),
        path=path,
    )


class AdaptiveBatchBackoff:
    """Effective-batch-size state machine for one scan.

    Starts at ``full`` (the scan's nominal batch size — which stays the
    checkpoint identity; backoff is internal to a dispatch). ``shrink``
    halves geometrically down to ``min_rows``; ``note_clean`` heals
    back up (doubling) after ``heal_after`` consecutive clean units,
    0/negative disables healing. ``align`` keeps sizes a multiple of
    the mesh's dp extent so sharded puts stay legal.

    Zero-cost default: until the first OOM, the scan's only extra work
    is one ``effective == full`` comparison per batch — no threads, no
    telemetry, no allocation.
    """

    __slots__ = ("full", "min_rows", "heal_after", "align",
                 "effective", "_clean")

    def __init__(
        self,
        full_rows: int,
        min_rows: int,
        heal_after: int = 0,
        align: int = 1,
    ):
        self.full = max(1, int(full_rows))
        self.align = max(1, int(align))
        self.min_rows = min(
            self.full, max(self.align, int(min_rows))
        )
        self.heal_after = int(heal_after)
        self.effective = self.full
        self._clean = 0

    @property
    def active(self) -> bool:
        return self.effective < self.full

    def _aligned(self, rows: int) -> int:
        return max(
            self.align, (rows // self.align) * self.align
        )

    def shrink(self, stage: str, batch_index: int) -> bool:
        """Halve the effective size after an OOM. Returns False when
        already at the floor (backoff exhausted: the caller
        quarantines)."""
        if self.effective <= self.min_rows:
            get_telemetry().event(
                "scan_memory_pressure",
                action="exhausted",
                stage=stage,
                batch_index=int(batch_index),
                effective_rows=int(self.effective),
            )
            return False
        previous = self.effective
        self.effective = max(
            self.min_rows, self._aligned(self.effective // 2)
        )
        self._clean = 0
        tm = get_telemetry()
        tm.counter("engine.batch_size_backoffs").inc()
        tm.metrics.gauge("engine.batch_rows_effective").set(
            self.effective
        )
        tm.event(
            "scan_memory_pressure",
            action="backoff",
            stage=stage,
            batch_index=int(batch_index),
            from_rows=int(previous),
            effective_rows=int(self.effective),
        )
        return True

    def note_clean(self) -> bool:
        """One unit completed without an OOM; heal (double) after
        ``heal_after`` consecutive clean units. Returns True when a
        heal happened."""
        if self.effective >= self.full or self.heal_after <= 0:
            return False
        self._clean += 1
        if self._clean < self.heal_after:
            return False
        self._clean = 0
        previous = self.effective
        self.effective = min(self.full, self._aligned(previous * 2))
        tm = get_telemetry()
        tm.metrics.gauge("engine.batch_rows_effective").set(
            self.effective
        )
        tm.event(
            "scan_memory_pressure",
            action="heal",
            from_rows=int(previous),
            effective_rows=int(self.effective),
        )
        return True


def make_backoff(
    batch_size: int, align: int = 1
) -> Optional[AdaptiveBatchBackoff]:
    """The configured backoff controller for one scan, or None when
    ``config.memory_backoff`` is off (dispatch failures then propagate
    exactly as before this layer existed)."""
    from deequ_tpu import config

    opts = config.options()
    if not opts.memory_backoff:
        return None
    return AdaptiveBatchBackoff(
        batch_size,
        opts.min_batch_rows,
        heal_after=opts.memory_heal_after_batches,
        align=align,
    )


def oom_probe_of(dataset: Any):
    """The dataset's fault-injection probe (``testing/faults.py``
    attaches one; real datasets have none). The engine calls
    ``probe(stage, index, rows)`` inside the guarded dispatch/transfer
    stages so an injected OOM rides the exact classification path a
    live one would."""
    return getattr(dataset, "oom_probe", None)
