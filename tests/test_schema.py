"""Row-level schema validation tests (reference test model:
RowLevelSchemaValidatorTest — SURVEY.md §1 L11, §2.5)."""

import pyarrow as pa
import pytest

from deequ_tpu import Dataset
from deequ_tpu.schema import (
    RowLevelSchema,
    RowLevelSchemaValidator,
)


class TestRowLevelSchemaValidator:
    def test_mixed_csv_style_validation(self):
        """The reference's canonical example: all-string input, typed
        schema, split into typed-valid and raw-invalid rows."""
        ds = Dataset.from_pydict(
            {
                "id": ["1", "2", "three", "4", None],
                "name": ["a", "bb", "ccc", None, "e"],
                "ts": [
                    "2024-01-01 00:00:00",
                    "2024-06-15 12:30:00",
                    "2024-01-01 00:00:00",
                    "not a date",
                    "2024-01-01 00:00:00",
                ],
            }
        )
        schema = (
            RowLevelSchema()
            .with_int_column("id", is_nullable=False)
            .with_string_column("name", is_nullable=True, max_length=2)
            .with_timestamp_column("ts", mask="yyyy-MM-dd HH:mm:ss")
        )
        result = RowLevelSchemaValidator.validate(ds, schema)
        # row0 ok; row1 ok; row2 id unparseable + name too long;
        # row3 bad ts; row4 id null (non-nullable)
        assert result.num_valid_rows == 2
        assert result.num_invalid_rows == 3
        valid = result.valid_rows.table
        assert pa.types.is_integer(valid.column("id").type)
        assert pa.types.is_timestamp(valid.column("ts").type)
        assert valid.column("id").to_pylist() == [1, 2]
        # invalid rows keep the RAW values for debugging
        invalid = result.invalid_rows.table
        assert invalid.column("id").to_pylist() == ["three", "4", None]

    def test_int_bounds(self):
        ds = Dataset.from_pydict({"x": ["5", "15", "-3", "7"]})
        schema = RowLevelSchema().with_int_column(
            "x", min_value=0, max_value=10
        )
        result = RowLevelSchemaValidator.validate(ds, schema)
        assert result.valid_rows.table.column("x").to_pylist() == [5, 7]

    def test_string_regex_and_lengths(self):
        ds = Dataset.from_pydict(
            {"code": ["AB-1", "XY-2", "bad", "AB-33", None]}
        )
        schema = RowLevelSchema().with_string_column(
            "code",
            is_nullable=False,
            min_length=4,
            max_length=5,
            matches=r"^[A-Z]{2}-\d+$",
        )
        result = RowLevelSchemaValidator.validate(ds, schema)
        assert result.valid_rows.table.column("code").to_pylist() == [
            "AB-1",
            "XY-2",
            "AB-33",
        ]

    def test_nullable_semantics(self):
        ds = Dataset.from_pydict({"x": ["1", None, "2"]})
        nullable = RowLevelSchema().with_int_column("x", is_nullable=True)
        strict = RowLevelSchema().with_int_column("x", is_nullable=False)
        assert RowLevelSchemaValidator.validate(ds, nullable).num_valid_rows == 3
        assert RowLevelSchemaValidator.validate(ds, strict).num_valid_rows == 2

    def test_decimal_precision_scale(self):
        ds = Dataset.from_pydict(
            {"d": ["12.34", "1.2", "123.45", "1.234", "x"]}
        )
        schema = RowLevelSchema().with_decimal_column(
            "d", precision=4, scale=2
        )
        result = RowLevelSchemaValidator.validate(ds, schema)
        # 123.45 has 3 integer digits (> precision-scale=2); 1.234 scale 3
        assert result.valid_rows.table.column("d").to_pylist() == [
            pytest.approx(12.34),
            pytest.approx(1.2),
        ]

    def test_fractional_column(self):
        ds = Dataset.from_pydict({"f": ["1.5", "2", "abc", "1e3"]})
        schema = RowLevelSchema().with_fractional_column(
            "f", is_nullable=False
        )
        result = RowLevelSchemaValidator.validate(ds, schema)
        assert result.valid_rows.table.column("f").to_pylist() == [
            1.5,
            2.0,
            1000.0,
        ]

    def test_typed_input_passthrough(self):
        """Already-typed columns validate on nullability alone."""
        ds = Dataset.from_pydict({"x": [1, 2, None]})
        schema = RowLevelSchema().with_int_column("x", is_nullable=False)
        result = RowLevelSchemaValidator.validate(ds, schema)
        assert result.num_valid_rows == 2

    def test_unknown_column_raises(self):
        ds = Dataset.from_pydict({"x": [1]})
        with pytest.raises(KeyError):
            RowLevelSchemaValidator.validate(
                ds, RowLevelSchema().with_int_column("nope")
            )

    def test_undeclared_columns_pass_through(self):
        ds = Dataset.from_pydict({"x": ["1", "2"], "extra": ["p", "q"]})
        schema = RowLevelSchema().with_int_column("x")
        result = RowLevelSchemaValidator.validate(ds, schema)
        assert result.valid_rows.table.column("extra").to_pylist() == [
            "p",
            "q",
        ]
