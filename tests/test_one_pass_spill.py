"""One-pass spill (analyzers/spill.py collectors): high-cardinality
grouping key extraction rides THE shared fused scan instead of one
deferred re-scan per plan, and every plan's sort finalize dispatches
before any result is fetched. Ground truth is the deferred per-plan
re-scan path itself (``one_pass_spill=False``), which these tests
require to agree EXACTLY — both forms feed byte-identical key vectors
to the same sort + segment-count programs."""

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    CountDistinct,
    Distinctness,
    Histogram,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.analyzers import spill as spill_mod
from deequ_tpu.data import Dataset
from deequ_tpu.telemetry import get_telemetry


class CountingDataset(Dataset):
    """Dataset that counts every traversal of the source, whichever
    door the engine walks through (resident chunks, streaming batches,
    or host record batches)."""

    def __init__(self, table):
        super().__init__(table)
        self.traversals = 0

    def device_scan_chunks(self, *args, **kwargs):
        self.traversals += 1
        return super().device_scan_chunks(*args, **kwargs)

    def device_batches(self, *args, **kwargs):
        self.traversals += 1
        return super().device_batches(*args, **kwargs)

    def record_batches(self, *args, **kwargs):
        self.traversals += 1
        return super().record_batches(*args, **kwargs)


def _counting(data) -> CountingDataset:
    return CountingDataset(Dataset.from_pydict(data)._table)


def _values(dataset, analyzers, **options):
    with config.configure(**options):
        ctx = AnalysisRunner.do_analysis_run(dataset, analyzers)
    out = {}
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out[a] = value.get()
    return out


def _assert_one_pass_matches_deferred(data, analyzers):
    """The load-bearing assertion: same metrics, exactly, both ways."""
    one = _values(Dataset.from_pydict(data), analyzers, one_pass_spill=True)
    per = _values(Dataset.from_pydict(data), analyzers, one_pass_spill=False)
    for a in analyzers:
        assert one[a] == per[a], (a, one[a], per[a])


def _mixed_suite_data(n=50_000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        # two independent high-cardinality int spill plans
        "id_a": rng.integers(0, 2**40, n).tolist(),
        "id_b": rng.integers(0, 2**40, n).tolist(),
        # a float spill plan
        "price": rng.normal(size=n).tolist(),
        # a dense plan and a scalar column
        "cat": rng.integers(0, 5, n).tolist(),
        "x": rng.normal(size=n).tolist(),
    }


MIXED_ANALYZERS = [
    Size(),
    Mean("x"),
    Completeness("price"),
    Uniqueness(["id_a"]),
    Distinctness(["id_b"]),
    CountDistinct(["price"]),
    Histogram("cat"),
]


class TestSingleTraversal:
    def test_mixed_suite_traverses_source_exactly_once(self):
        """Scalars + dense grouping + THREE spill plans = one pass."""
        ds = _counting(_mixed_suite_data())
        tm = get_telemetry()
        before = tm.metrics.snapshot()["counters"].get(
            "engine.data_passes", 0
        )
        with config.configure(one_pass_spill=True):
            ctx = AnalysisRunner.do_analysis_run(ds, MIXED_ANALYZERS)
        after = tm.metrics.snapshot()["counters"].get(
            "engine.data_passes", 0
        )
        assert ds.traversals == 1
        assert after - before == 1
        for a in MIXED_ANALYZERS:
            assert ctx.metric(a).value.is_success, a

    def test_deferred_re_scans_per_plan(self):
        """The escape hatch still costs one extra traversal per spill
        plan — the behavior the collector form exists to remove."""
        ds = _counting(_mixed_suite_data())
        with config.configure(one_pass_spill=False):
            AnalysisRunner.do_analysis_run(ds, MIXED_ANALYZERS)
        assert ds.traversals == 4  # shared scan + 3 spill re-reads

    def test_mixed_suite_metrics_identical(self):
        _assert_one_pass_matches_deferred(
            _mixed_suite_data(), MIXED_ANALYZERS
        )


class TestDifferentialSingleColumn:
    def test_int_keys(self):
        rng = np.random.default_rng(1)
        data = {"k": rng.integers(-(2**40), 2**40, 30_000).tolist()}
        _assert_one_pass_matches_deferred(
            data, [Uniqueness(["k"]), Distinctness(["k"]),
                   CountDistinct(["k"])]
        )

    def test_f32_keys(self):
        rng = np.random.default_rng(2)
        vals = rng.normal(size=20_000).astype(np.float32)
        vals[::9] = np.float32(0.0)
        vals[1::9] = np.float32(-0.0)
        vals[2::9] = np.float32("nan")
        data = {"k": vals.tolist()}
        _assert_one_pass_matches_deferred(
            data, [Distinctness(["k"]), CountDistinct(["k"])]
        )

    def test_f64_keys_with_nan_and_signed_zero(self):
        rng = np.random.default_rng(3)
        vals = rng.normal(size=20_000)
        vals[::7] = np.nan
        vals[1::11] = 0.0
        vals[2::13] = -0.0
        data = {"k": vals.tolist()}
        _assert_one_pass_matches_deferred(
            data, [Uniqueness(["k"]), CountDistinct(["k"])]
        )

    def test_f64_forced_host_bit_packing(self, monkeypatch):
        """The TPU path: canonical u64 bits packed on the host via the
        ``u64bits`` column repr instead of a device bitcast."""
        monkeypatch.setattr(spill_mod, "_FORCE_HOST_F64_BITS", True)
        rng = np.random.default_rng(4)
        vals = rng.normal(size=20_000)
        vals[::7] = np.nan
        data = {"k": vals.tolist()}
        tm = get_telemetry()
        before = tm.metrics.snapshot()["counters"].get(
            "engine.data_passes", 0
        )
        one = _values(
            Dataset.from_pydict(data),
            [Size(), Uniqueness(["k"])],
            one_pass_spill=True,
        )
        after = tm.metrics.snapshot()["counters"].get(
            "engine.data_passes", 0
        )
        assert after - before == 1  # host bit packing stays one-pass
        per = _values(
            Dataset.from_pydict(data),
            [Size(), Uniqueness(["k"])],
            one_pass_spill=False,
        )
        assert one == per

    def test_include_nulls_histogram(self):
        rng = np.random.default_rng(5)
        vals = rng.normal(size=20_000)
        data = {
            "k": [
                None if i % 5 == 0 else float(v)
                for i, v in enumerate(vals)
            ]
        }
        _assert_one_pass_matches_deferred(
            data, [Histogram("k", max_detail_bins=25)]
        )

    def test_where_filter(self):
        rng = np.random.default_rng(6)
        data = {
            "k": rng.normal(size=20_000).tolist(),
            "gate": rng.integers(0, 2, 20_000).tolist(),
        }
        _assert_one_pass_matches_deferred(
            data, [Uniqueness(["k"], where="gate = 1")]
        )


class TestDifferentialJoint:
    def test_joint_one_lane(self):
        rng = np.random.default_rng(7)
        n = 20_000
        data = {
            "a": rng.integers(0, 300, n).tolist(),
            "b": rng.integers(0, 300, n).tolist(),
        }
        analyzers = [Uniqueness(["a", "b"]), Distinctness(["a", "b"])]
        # force the dense path out: joint ~90k slots > budget
        one = _values(
            Dataset.from_pydict(data), analyzers,
            one_pass_spill=True, dense_grouping_budget_bytes=4 * 1024,
        )
        per = _values(
            Dataset.from_pydict(data), analyzers,
            one_pass_spill=False, dense_grouping_budget_bytes=4 * 1024,
        )
        assert one == per

    def test_joint_two_lanes(self):
        """Four ~55k-cardinality columns: joint radix product past one
        u64 lane, keys ride TWO collector lanes."""
        rng = np.random.default_rng(8)
        n = 30_000
        data = {
            f"c{i}": rng.integers(0, 55_000, n).tolist()
            for i in range(4)
        }
        analyzers = [Uniqueness(["c0", "c1", "c2", "c3"])]
        one = _values(
            Dataset.from_pydict(data), analyzers,
            one_pass_spill=True, dense_grouping_budget_bytes=4 * 1024,
        )
        per = _values(
            Dataset.from_pydict(data), analyzers,
            one_pass_spill=False, dense_grouping_budget_bytes=4 * 1024,
        )
        assert one == per


class TestDifferentialMesh:
    def test_mesh_single_column(self, cpu_mesh):
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(9)
        n = 40_000
        data = {
            "id": rng.integers(0, 2**40, n).tolist(),
            "x": rng.normal(size=n).tolist(),
        }
        analyzers = [Size(), Mean("x"), Uniqueness(["id"]),
                     CountDistinct(["id"])]

        def run(one_pass):
            ds = Dataset.from_pydict(data)
            engine = AnalysisEngine(mesh=cpu_mesh)
            tm = get_telemetry()
            before = tm.metrics.snapshot()["counters"].get(
                "engine.data_passes", 0
            )
            with config.configure(one_pass_spill=one_pass):
                ctx = AnalysisRunner.do_analysis_run(
                    ds, analyzers, engine=engine
                )
            passes = tm.metrics.snapshot()["counters"].get(
                "engine.data_passes", 0
            ) - before
            out = {}
            for a in analyzers:
                value = ctx.metric(a).value
                assert value.is_success, (a, value)
                out[a] = value.get()
            return out, passes

        one, p1 = run(True)
        per, p0 = run(False)
        assert one == per
        assert p1 == 1
        assert p0 == 2  # shared scan + the spill plan's mesh staging

    def test_mesh_joint_two_lanes(self, cpu_mesh):
        from deequ_tpu.engine.scan import AnalysisEngine

        rng = np.random.default_rng(10)
        n = 30_000
        data = {
            f"c{i}": rng.integers(0, 55_000, n).tolist()
            for i in range(4)
        }
        analyzers = [Uniqueness(["c0", "c1", "c2", "c3"])]

        def run(one_pass):
            ds = Dataset.from_pydict(data)
            engine = AnalysisEngine(mesh=cpu_mesh)
            with config.configure(
                one_pass_spill=one_pass,
                dense_grouping_budget_bytes=4 * 1024,
            ):
                ctx = AnalysisRunner.do_analysis_run(
                    ds, analyzers, engine=engine
                )
            value = ctx.metric(analyzers[0]).value
            assert value.is_success, value
            return value.get()

        assert run(True) == run(False)
