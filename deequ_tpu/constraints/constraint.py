"""Constraints: assertions over metrics, evaluated against an
AnalyzerContext.

Reference: ``src/main/scala/com/amazon/deequ/constraints/`` (SURVEY.md
§2.5): ``AnalysisBasedConstraint[S, M, V]`` pairs an analyzer with an
assertion ``V => Boolean`` plus an optional value picker; evaluation is a
pure metric lookup + assertion — no data access. ``NamedConstraint``
decorates with a display name.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.metrics.metric import Metric

MISSING_ANALYSIS_MSG = "Missing Analysis, can't run the constraint!"
ASSERTION_EXCEPTION_MSG = "Can't execute the assertion"


class ConstraintStatus(enum.Enum):
    SUCCESS = "Success"
    FAILURE = "Failure"


@dataclass
class ConstraintResult:
    constraint: "Constraint"
    status: ConstraintStatus
    message: Optional[str] = None
    metric: Optional[Metric] = None


class Constraint:
    """Base: evaluate against the analyzer context."""

    def evaluate(self, analysis_result) -> ConstraintResult:
        raise NotImplementedError


class ConstraintDecorator(Constraint):
    def __init__(self, inner: Constraint):
        self._inner = inner

    @property
    def inner(self) -> Constraint:
        if isinstance(self._inner, ConstraintDecorator):
            return self._inner.inner
        return self._inner

    def evaluate(self, analysis_result) -> ConstraintResult:
        result = self._inner.evaluate(analysis_result)
        result.constraint = self
        return result


class NamedConstraint(ConstraintDecorator):
    def __init__(self, inner: Constraint, name: str):
        super().__init__(inner)
        self._name = name

    def __repr__(self) -> str:
        return self._name

    def __str__(self) -> str:
        return self._name


class AnalysisBasedConstraint(Constraint):
    """analyzer + assertion (+ value picker) -> ConstraintResult.

    - missing metric in the context -> FAILURE(MissingAnalysis)
    - failed metric -> FAILURE carrying the metric's exception message
    - value-picker/assertion exception -> FAILURE with the message
    - assertion false -> FAILURE with actual value; true -> SUCCESS
    """

    def __init__(
        self,
        analyzer: Analyzer,
        assertion: Callable[[Any], bool],
        value_picker: Optional[Callable[[Any], Any]] = None,
        hint: Optional[str] = None,
    ):
        self.analyzer = analyzer
        self.assertion = assertion
        self.value_picker = value_picker
        self.hint = hint

    def evaluate(self, analyzer_context) -> ConstraintResult:
        metric = analyzer_context.metric(self.analyzer)
        if metric is None:
            return ConstraintResult(
                self, ConstraintStatus.FAILURE, MISSING_ANALYSIS_MSG, None
            )
        return self._pick_value_and_assert(metric)

    def _pick_value_and_assert(self, metric: Metric) -> ConstraintResult:
        if metric.value.is_failure:
            message = f"metric computation failed: {metric.value.exception}"
            if self.hint:
                message += f" {self.hint}"
            return ConstraintResult(
                self, ConstraintStatus.FAILURE, message, metric
            )
        try:
            raw = metric.value.get()
            value = self.value_picker(raw) if self.value_picker else raw
        except Exception as exc:  # noqa: BLE001
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"{ASSERTION_EXCEPTION_MSG}: {exc}",
                metric,
            )
        try:
            ok = bool(self.assertion(value))
        except Exception as exc:  # noqa: BLE001
            return ConstraintResult(
                self,
                ConstraintStatus.FAILURE,
                f"{ASSERTION_EXCEPTION_MSG}: {exc}",
                metric,
            )
        if ok:
            return ConstraintResult(
                self, ConstraintStatus.SUCCESS, None, metric
            )
        message = (
            f"Value: {value} does not meet the constraint requirement!"
        )
        if self.hint:
            message += f" {self.hint}"
        return ConstraintResult(
            self, ConstraintStatus.FAILURE, message, metric
        )

    def __repr__(self) -> str:
        return (
            f"AnalysisBasedConstraint({self.analyzer!r})"
        )
