"""DP-mesh tests on the 8-virtual-CPU-device mesh: sharded execution must
equal single-device, and the explicit shard_map + monoid-all-reduce step
must compile and agree (SURVEY.md §4: the no-real-cluster multi-device
story)."""

import jax
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.engine import AnalysisEngine, monoid_all_reduce
from fixtures import big_numeric


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Sum("x"),
    Minimum("x"),
    Maximum("x"),
    StandardDeviation("x"),
]


def test_mesh_equals_single_device(cpu_mesh):
    data = big_numeric(50_000)
    ctx_single = AnalysisRunner.do_analysis_run(
        data, ANALYZERS, engine=AnalysisEngine()
    )
    ctx_mesh = AnalysisRunner.do_analysis_run(
        data,
        ANALYZERS,
        engine=AnalysisEngine(mesh=cpu_mesh, batch_size=8_192),
    )
    for analyzer in ANALYZERS:
        a = ctx_single.metric(analyzer).value.get()
        b = ctx_mesh.metric(analyzer).value.get()
        assert a == pytest.approx(b, rel=1e-9), analyzer


def test_explicit_shard_map_step(cpu_mesh):
    """The explicit-SPMD path: per-shard update + monoid all-reduce."""
    data = big_numeric(16_384)
    planned = [(a, a.make_ops(data)) for a in ANALYZERS]
    engine = AnalysisEngine(mesh=cpu_mesh)
    step = engine.build_sharded_step(data, planned, cpu_mesh)

    requests = [
        r for a, _ in planned for r in a.device_requests(data)
    ]
    (batch,) = list(data.device_batches(requests, 16_384))
    states = tuple(ops.init() for _, ops in planned)
    out_states = step(states, batch)

    ctx = AnalysisRunner.do_analysis_run(data, ANALYZERS)
    for (analyzer, _), state in zip(planned, out_states):
        metric = analyzer.compute_metric_from_state(jax.device_get(state))
        expected = ctx.metric(analyzer).value.get()
        assert metric.value.get() == pytest.approx(expected, rel=1e-9)


def test_mesh_grouping_equals_single_device(cpu_mesh):
    """Dense frequency scans under the mesh (NamedSharding batches, XLA
    collectives) must equal the single-device result."""
    from deequ_tpu import Dataset
    from deequ_tpu.analyzers import CountDistinct, Histogram, Uniqueness

    rng = np.random.default_rng(9)
    data = Dataset.from_pydict(
        {"g": rng.integers(0, 500, 40_000), "h": rng.choice(["a", "b", "c"], 40_000)}
    )
    analyzers = [CountDistinct("g"), Uniqueness("g"), Histogram("h")]
    single = AnalysisRunner.do_analysis_run(data, analyzers)
    meshed = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine(mesh=cpu_mesh, batch_size=8_192)
    )
    for a in (CountDistinct("g"), Uniqueness("g")):
        assert single.metric(a).value.get() == pytest.approx(
            meshed.metric(a).value.get()
        ), a
    hs = single.metric(Histogram("h")).value.get()
    hm = meshed.metric(Histogram("h")).value.get()
    assert {k: v.absolute for k, v in hs.values.items()} == {
        k: v.absolute for k, v in hm.values.items()
    }


def test_mesh_sketches_equal_single_device(cpu_mesh):
    """Sketch/LUT families NAMED in the mesh regression file (VERDICT
    r4 weak #6): HLL (numeric + dict-encoded), DataType, KLL,
    CustomSql under the mesh vs single-device. HLL registers and
    DataType counts merge exactly (max / add monoids), so equality is
    exact; KLL merged across shard boundaries is a different (valid)
    sketch, so it is held to the rank-error envelope instead."""
    from deequ_tpu import Dataset
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        ApproxQuantile,
        CustomSql,
    )
    from deequ_tpu.analyzers.datatype import DataType

    rng = np.random.default_rng(21)
    n = 40_000
    xs = rng.normal(50.0, 9.0, n)
    data = Dataset.from_pydict(
        {
            "x": xs,
            "k": rng.integers(0, 30_000, n),
            "s": rng.choice(["1", "2.5", "x", "true", ""], n),
        }
    )
    exact = [
        ApproxCountDistinct("x"),
        ApproxCountDistinct("k"),
        ApproxCountDistinct("s"),
        DataType("s"),
        CustomSql("SUM(x) / COUNT(*)"),
    ]
    quantile = ApproxQuantile("x", 0.5)
    analyzers = exact + [quantile]
    single = AnalysisRunner.do_analysis_run(data, analyzers)
    meshed = AnalysisRunner.do_analysis_run(
        data,
        analyzers,
        engine=AnalysisEngine(mesh=cpu_mesh, batch_size=8_192),
    )
    for a in exact[:3] + exact[4:]:
        got = meshed.metric(a).value.get()
        want = single.metric(a).value.get()
        assert got == pytest.approx(want, rel=1e-9), (a, got, want)
    ds_hist = single.metric(DataType("s")).value.get()
    dm_hist = meshed.metric(DataType("s")).value.get()
    assert {k: v.absolute for k, v in ds_hist.values.items()} == {
        k: v.absolute for k, v in dm_hist.values.items()
    }
    # KLL: both sketches answer within the rank-error envelope
    got_q = meshed.metric(quantile).value.get()
    want_q = single.metric(quantile).value.get()
    srt = np.sort(xs)
    for q in (got_q, want_q):
        rank = np.searchsorted(srt, q) / n
        assert abs(rank - 0.5) < 0.02, (q, rank)


def test_incremental_tree_merge_many_states(tmp_path):
    """run_on_aggregated_states over MANY providers (tree fold)."""
    import os

    from deequ_tpu import Dataset, FileSystemStateProvider
    from deequ_tpu.analyzers import CountDistinct, Mean, Size

    analyzers = [Size(), Mean("x"), CountDistinct("x")]
    providers = []
    total = 0
    for i in range(9):
        ds = Dataset.from_pydict(
            {"x": list(np.arange(i * 10.0, i * 10.0 + 10.0))}
        )
        p = FileSystemStateProvider(os.path.join(tmp_path, f"s{i}"))
        AnalysisRunner.do_analysis_run(ds, analyzers, save_states_with=p)
        providers.append(p)
        total += 10
    schema = Dataset.from_pydict({"x": [1.0]}).schema
    ctx = AnalysisRunner.run_on_aggregated_states(schema, analyzers, providers)
    assert ctx.metric(Size()).value.get() == total
    assert ctx.metric(CountDistinct("x")).value.get() == 90.0
    assert ctx.metric(Mean("x")).value.get() == pytest.approx(44.5)
