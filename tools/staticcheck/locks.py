"""Lock-discipline analyzer: per-class protected-attribute inference
plus a cross-class lock-acquisition graph.

Scope: the threaded modules — ``deequ_tpu/service/`` and the engine's
deadline/resilience/scan machinery. Two rules:

``lock-discipline`` — for every class owning a ``threading.Lock``/
``RLock``/``Condition`` attribute, the protected set is inferred as
"attributes written inside ``with self._lock:`` (or ``self._cond``,
which aliases the same lock per the repo's ``Condition(self._lock)``
convention) in any non-``__init__`` method, or anywhere in a
``*_locked`` method (the caller-holds-the-lock naming convention)".
Every read or write of a protected attribute outside a lock scope and
outside ``__init__``/``*_locked`` methods is flagged. Lock-free read
paths that are deliberate (e.g. a monitoring ``status`` property
reading a monotonic state machine) take a reasoned waiver.

``lock-order`` — a digraph over class locks: an edge A→B means some
method acquires B's lock while lexically holding A's. Built from a
flow-insensitive type environment (annotations, dataclass fields,
constructor assignments) and per-method acquisition summaries computed
to a fixed point, so ``RunQueue._resolve_dead`` calling
``handle._finish`` (which takes ``RunHandle._lock``) contributes
RunQueue→RunHandle. A cycle is a lock-order inversion — two threads
entering from opposite ends deadlock — and fails the build. Same-class
edges are NOT emitted: parent/child instances of one class share a
graph node and re-entry is already visible as a self-deadlock at
runtime, while the legitimate pattern (iterate children outside the
lock) would false-positive.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    annotation_class,
    dotted_name,
    register,
)

SCOPE_PREFIXES = ("deequ_tpu/service/",)
SCOPE_FILES = (
    "deequ_tpu/engine/deadline.py",
    "deequ_tpu/engine/resilience.py",
    "deequ_tpu/engine/scan.py",
)

LOCK_FACTORY_TAILS = frozenset({"Lock", "RLock", "Condition"})
INIT_METHODS = frozenset({"__init__", "__post_init__", "__del__"})
#: mutating container methods — a call like ``self._queued.append(x)``
#: is a WRITE to ``_queued`` for protection inference
MUTATORS = frozenset(
    {
        "append", "appendleft", "extend", "insert", "remove", "pop",
        "popleft", "popitem", "clear", "add", "discard", "update",
        "setdefault", "move_to_end", "sort", "reverse",
    }
)


def _in_scope(rel: str) -> bool:
    return rel in SCOPE_FILES or any(
        rel.startswith(p) for p in SCOPE_PREFIXES
    )


@dataclass
class ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    lock_attrs: Set[str] = field(default_factory=set)  # incl. aliases
    protected: Set[str] = field(default_factory=set)
    #: attr -> class name, from annotations/constructor calls
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_class(node: ast.ClassDef, rel: str) -> ClassInfo:
    info = ClassInfo(name=node.name, rel=rel, node=node)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
        elif isinstance(item, ast.AnnAssign) and isinstance(
            item.target, ast.Name
        ):
            cls = annotation_class(item.annotation)
            if cls:
                info.attr_types[item.target.id] = cls
    # lock attributes + constructor-derived attr types
    for method in info.methods.values():
        args = getattr(method, "args", None)
        param_types: Dict[str, str] = {}
        if args is not None:
            for arg in list(args.args) + list(args.kwonlyargs):
                cls = annotation_class(arg.annotation)
                if cls:
                    param_types[arg.arg] = cls
        for sub in ast.walk(method):
            target_attr = None
            value = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                target_attr = _self_attr(sub.targets[0])
                value = sub.value
            elif isinstance(sub, ast.AnnAssign):
                target_attr = _self_attr(sub.target)
                value = sub.value
                cls = annotation_class(sub.annotation)
                if target_attr and cls:
                    info.attr_types[target_attr] = cls
            if target_attr is None or value is None:
                continue
            if isinstance(value, ast.Name) and value.id in param_types:
                # ``self._q = q`` with an annotated parameter ``q: Queue``
                info.attr_types.setdefault(
                    target_attr, param_types[value.id]
                )
            if isinstance(value, ast.Call):
                callee = dotted_name(value.func) or ""
                tail = callee.split(".")[-1]
                if tail in LOCK_FACTORY_TAILS:
                    info.lock_attrs.add(target_attr)
                    # Condition(self._lock) aliases the named lock; a
                    # bare Condition() is its own lock — either way the
                    # attr is a lock handle on this class's node
                elif tail and tail[0].isupper():
                    info.attr_types.setdefault(target_attr, tail)
    return info


@dataclass
class _Access:
    attr: str
    line: int
    write: bool


class _MethodScanner(ast.NodeVisitor):
    """One pass over a method: attribute accesses tagged with whether
    the class lock is lexically held, plus calls made while holding."""

    def __init__(self, info: ClassInfo, held_at_entry: bool) -> None:
        self.info = info
        self.held = held_at_entry
        self.locked_accesses: List[_Access] = []
        self.unlocked_accesses: List[_Access] = []
        #: (callee dotted name, line, held) — for the lock graph
        self.calls: List[Tuple[str, int, bool]] = []

    def _is_lock_with(self, item: ast.withitem) -> bool:
        attr = _self_attr(item.context_expr)
        return attr is not None and attr in self.info.lock_attrs

    def visit_With(self, node: ast.With) -> None:
        takes = any(self._is_lock_with(item) for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        prev = self.held
        if takes:
            self.held = True
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    def _record(self, attr: str, line: int, write: bool) -> None:
        if attr in self.info.lock_attrs:
            return
        access = _Access(attr=attr, line=line, write=write)
        (self.locked_accesses if self.held
         else self.unlocked_accesses).append(access)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(
                attr, node.lineno, isinstance(node.ctx, (ast.Store, ast.Del))
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func)
        if callee:
            self.calls.append((callee, node.lineno, self.held))
            # mutating method on a self attribute counts as a write
            parts = callee.split(".")
            if (
                len(parts) == 3
                and parts[0] == "self"
                and parts[2] in MUTATORS
            ):
                self._record(parts[1], node.lineno, True)
        self.generic_visit(node)

    # nested defs inherit the held state they're defined under (they
    # almost always run inline in this codebase); don't reset it
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)


def _scan_method(
    info: ClassInfo, name: str, method: ast.AST
) -> _MethodScanner:
    held_at_entry = name.endswith("_locked")
    scanner = _MethodScanner(info, held_at_entry)
    for stmt in method.body:  # skip decorators/defaults
        scanner.visit(stmt)
    return scanner


class LockDisciplineAnalyzer(Analyzer):
    name = "locks"
    rules = ("lock-discipline", "lock-order")
    description = (
        "lock-protected attribute accesses outside lock scope; "
        "cross-class lock-acquisition cycles"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        classes: Dict[str, ClassInfo] = {}
        scanners: Dict[Tuple[str, str], _MethodScanner] = {}
        for sf in files:
            if not _in_scope(sf.rel) or sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    info = _collect_class(node, sf.rel)
                    classes[info.name] = info
        for info in classes.values():
            if not info.lock_attrs:
                continue
            for mname, method in info.methods.items():
                scanners[(info.name, mname)] = _scan_method(
                    info, mname, method
                )
        # protected set: attrs WRITTEN under the lock (init exempt)
        for info in classes.values():
            for (cname, mname), sc in scanners.items():
                if cname != info.name or mname in INIT_METHODS:
                    continue
                for access in sc.locked_accesses:
                    if access.write:
                        info.protected.add(access.attr)
        # rule 1: protected-attr access outside lock scope
        for (cname, mname), sc in sorted(scanners.items()):
            info = classes[cname]
            if mname in INIT_METHODS:
                continue
            seen: Set[Tuple[str, int]] = set()
            for access in sc.unlocked_accesses:
                if access.attr not in info.protected:
                    continue
                dedup = (access.attr, access.line)
                if dedup in seen:
                    continue
                seen.add(dedup)
                kind = "write to" if access.write else "read of"
                yield Finding(
                    rule="lock-discipline",
                    path=info.rel,
                    line=access.line,
                    message=(
                        f"{kind} lock-protected attribute "
                        f"'{cname}.{access.attr}' outside lock scope in "
                        f"'{mname}' (protected: assigned under "
                        f"'with self.{sorted(info.lock_attrs)[0]}:')"
                    ),
                    symbol=access.attr,
                )
        yield from self._lock_order(classes, scanners)

    # -- lock-order graph --------------------------------------------------

    def _lock_order(
        self,
        classes: Dict[str, ClassInfo],
        scanners: Dict[Tuple[str, str], _MethodScanner],
    ) -> Iterable[Finding]:
        locked_classes = {
            name for name, info in classes.items() if info.lock_attrs
        }

        def resolve(cname: str, callee: str) -> Optional[Tuple[str, str]]:
            """(class, method) a dotted callee resolves to, using the
            class's attr/param type environment."""
            parts = callee.split(".")
            if parts[0] in ("self", "cls"):
                if len(parts) == 2 and parts[1] in classes[cname].methods:
                    return (cname, parts[1])
                if len(parts) == 3:
                    attr_cls = classes[cname].attr_types.get(parts[1])
                    if attr_cls in classes and parts[2] in classes[
                        attr_cls
                    ].methods:
                        return (attr_cls, parts[2])
                return None
            if len(parts) == 2:
                # local var typed by annotation is out of reach here;
                # fall back to "any in-scope class with this method
                # whose name matches a known type of the base name"
                base_cls = _PARAM_TYPES.get((cname, parts[0]))
                if base_cls in classes and parts[1] in classes[
                    base_cls
                ].methods:
                    return (base_cls, parts[1])
            return None

        # parameter/local type environment per class, from annotations
        global _PARAM_TYPES
        _PARAM_TYPES = {}
        for cname, info in classes.items():
            for method in info.methods.values():
                args = getattr(method, "args", None)
                if args is None:
                    continue
                for arg in list(args.args) + list(args.kwonlyargs):
                    cls = annotation_class(arg.annotation)
                    if cls:
                        _PARAM_TYPES[(cname, arg.arg)] = cls
                for sub in ast.walk(method):
                    if isinstance(sub, ast.Assign) and len(
                        sub.targets
                    ) == 1 and isinstance(sub.targets[0], ast.Name):
                        if isinstance(sub.value, ast.Call):
                            tail = (
                                dotted_name(sub.value.func) or ""
                            ).split(".")[-1]
                            if tail in classes:
                                _PARAM_TYPES[
                                    (cname, sub.targets[0].id)
                                ] = tail
                        else:
                            src = dotted_name(sub.value)
                            if src:
                                sparts = src.split(".")
                                if sparts[0] == "self" and len(sparts) == 2:
                                    t = info.attr_types.get(sparts[1])
                                    if t:
                                        _PARAM_TYPES[
                                            (cname, sub.targets[0].id)
                                        ] = t
                    elif isinstance(sub, ast.AnnAssign) and isinstance(
                        sub.target, ast.Name
                    ):
                        cls = annotation_class(sub.annotation)
                        if cls:
                            _PARAM_TYPES[(cname, sub.target.id)] = cls

        # acquisition summaries to a fixed point: lock classes a call
        # to (class, method) may take internally
        acquires: Dict[Tuple[str, str], Set[str]] = {
            key: set() for key in scanners
        }
        for (cname, mname), sc in scanners.items():
            if any(
                a for a in sc.locked_accesses
            ) or _takes_lock_directly(sc):
                if not mname.endswith("_locked"):
                    acquires[(cname, mname)].add(cname)
        changed = True
        while changed:
            changed = False
            for (cname, mname), sc in scanners.items():
                for callee, _line, _held in sc.calls:
                    target = resolve(cname, callee)
                    if target is None or target not in acquires:
                        continue
                    add = acquires[target] - acquires[(cname, mname)]
                    if add:
                        acquires[(cname, mname)] |= add
                        changed = True
        # edges: holding A, acquire B (A != B)
        edges: Dict[str, Set[str]] = {}
        edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for (cname, mname), sc in scanners.items():
            for callee, line, held in sc.calls:
                if not held:
                    continue
                target = resolve(cname, callee)
                if target is None or target not in acquires:
                    continue
                for acquired in acquires[target]:
                    if acquired == cname:
                        continue
                    edges.setdefault(cname, set()).add(acquired)
                    edge_sites.setdefault(
                        (cname, acquired), (classes[cname].rel, line)
                    )
        # cycle detection (DFS)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {name: WHITE for name in locked_classes}
        stack: List[str] = []
        cycles: List[List[str]] = []

        def dfs(u: str) -> None:
            color[u] = GRAY
            stack.append(u)
            for v in sorted(edges.get(u, ())):
                if v not in color:
                    continue
                if color[v] == GRAY:
                    cycles.append(stack[stack.index(v):] + [v])
                elif color[v] == WHITE:
                    dfs(v)
            stack.pop()
            color[u] = BLACK

        for name in sorted(locked_classes):
            if color[name] == WHITE:
                dfs(name)
        for cycle in cycles:
            first_edge = (cycle[0], cycle[1])
            rel, line = edge_sites.get(first_edge, ("", 0))
            yield Finding(
                rule="lock-order",
                path=rel or classes[cycle[0]].rel,
                line=line,
                message=(
                    "lock-order inversion: acquisition cycle "
                    + " -> ".join(cycle)
                    + " — two threads entering from opposite ends deadlock"
                ),
                symbol=cycle[0],
            )


def _takes_lock_directly(sc: _MethodScanner) -> bool:
    """Whether the method body contains a ``with self.<lock>:`` (even
    with no protected accesses inside)."""
    # locked_accesses non-empty implies yes; also detect empty-bodied
    # acquisitions via the calls list: acquire()/wait() on a lock attr
    for callee, _line, _held in sc.calls:
        parts = callee.split(".")
        if (
            len(parts) == 3
            and parts[0] == "self"
            and parts[1] in sc.info.lock_attrs
            and parts[2] in ("acquire", "wait", "wait_for")
        ):
            return True
    return bool(sc.locked_accesses)


_PARAM_TYPES: Dict[Tuple[str, str], str] = {}


register(LockDisciplineAnalyzer())
