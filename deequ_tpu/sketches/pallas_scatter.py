"""Pallas scatter-max backend for the HLL register build.

tools/scatter_probe.py measured the XLA register scatter at ~145 M
elem/s across every formulation and found one Pallas variant that
beats it: a single SMEM stream of packed ``idx << 6 | rho`` words,
unroll-16 scalar loop, skip-cold stores (1.1-1.15x at B=2^21,
M=2^14 — docs/PERF.md "Pallas scatter kernel probe"). This module
ports that kernel behind ``config.pallas_scatter`` and generalizes it
to the production shape: C columns scattered per fused-scan step.

Layout constraints (probed on the real chip, encoded here):

- the register file must live in SMEM (scalar VMEM stores are
  unsupported by Mosaic), and SMEM is small — a flat (C*M,) register
  file for C=40 would need 2.6 MB, so the kernel runs a (C, G) grid
  with ONE (1, M) = 64 KB register block per column, revisited across
  the G chunk steps (grid iterates the last dimension fastest);
- BlockSpec index maps must return i32 (x64 is on; a literal 0 traces
  as i64 and Mosaic fails to legalize the index map);
- inputs stream as (1, CHUNK) SMEM blocks (grid-pipelined DMA).

The dispatch contract: :func:`scatter_max` returns ``None`` whenever
the Pallas path is off or unavailable and the caller (sketches/hll.py)
falls back to the XLA scatter. Availability is probed ONCE per process
by compiling AND running a tiny kernel end-to-end — Mosaic failures
surface at compile time, not trace time, so executing is the only
reliable probe. On CPU the probe fails fast and everything falls back;
set ``DEEQU_TPU_PALLAS_INTERPRET=1`` to run the kernel through the
Pallas interpreter instead (slow, but lets the CPU differential tests
exercise the real kernel logic — tests/test_fastpath_differential.py).

Both paths are bit-identical: max is commutative/associative and the
padded tail scatters ``rho=0`` into register 0, a no-op against the
zero-initialized file.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu import config

# packed words streamed per grid step: 32 KB of SMEM at i32, the
# probe's best chunk (c13); shorter batches use the next power of two
CHUNK = 1 << 13
# probe's best unroll: elements per fori iteration
UNROLL = 16


def _interpret_forced() -> bool:
    return os.environ.get("DEEQU_TPU_PALLAS_INTERPRET", "0") == "1"


def _tracing() -> bool:
    """True while inside a jit trace — the availability probe must run
    a real kernel, which is impossible mid-trace."""
    try:
        from jax import core

        return not core.trace_state_clean()
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _make_call(cols: int, g: int, chunk: int, unroll: int, m: int,
               interpret: bool):
    """Build the (C, G)-grid packed scatter-max pallas_call:
    (cols, g*chunk) i32 packed words -> (cols, m) i32 registers."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(packed_ref, reg_ref):
        # fresh column block: zero the register file before the first
        # chunk lands (the block is revisited for all g of this column)
        @pl.when(pl.program_id(1) == 0)
        def _init():
            def z(i, _):
                reg_ref[0, i] = jnp.int32(0)
                return jnp.int32(0)

            jax.lax.fori_loop(jnp.int32(0), jnp.int32(m), z, jnp.int32(0))

        def body(i, _):
            base = i * jnp.int32(unroll)
            for u in range(unroll):
                w = packed_ref[0, base + u]
                r = jax.lax.shift_right_logical(w, jnp.int32(6))
                v = jnp.bitwise_and(w, jnp.int32(63))
                cur = reg_ref[0, r]

                @pl.when(v > cur)
                def _store():
                    reg_ref[0, r] = v

            return jnp.int32(0)

        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(chunk // unroll), body, jnp.int32(0)
        )

    return pl.pallas_call(
        kernel,
        grid=(cols, g),
        in_specs=[
            pl.BlockSpec(
                (1, chunk), lambda c, gg: (c, gg), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, m), lambda c, gg: (c, jnp.int32(0)), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((cols, m), jnp.int32),
        interpret=interpret,
    )


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _scatter_max_call(idx, rho, m: int, interpret: bool):
    """(C, B) i32 idx/rho -> (C, m) i32 registers via the kernel,
    padding B up to a chunk multiple with (idx=0, rho=0) no-ops."""
    cols, b = idx.shape
    chunk = max(UNROLL, min(CHUNK, _pow2_at_least(b)))
    bp = -(-b // chunk) * chunk
    packed = jnp.bitwise_or(
        jnp.left_shift(idx.astype(jnp.int32), 6), rho.astype(jnp.int32)
    )
    if bp != b:
        packed = jnp.pad(packed, ((0, 0), (0, bp - b)))
    call = _make_call(cols, bp // chunk, chunk, UNROLL, m, interpret)
    return call(packed)


# probe verdict per interpret mode; populated lazily, reset by tests
_PROBE: Dict[bool, bool] = {}


def available() -> bool:
    """Can the kernel compile and run on this backend? Probed once
    end-to-end with a tiny shape; never probes mid-trace (returns
    False without caching so a later eager call can still succeed)."""
    interpret = _interpret_forced()
    hit = _PROBE.get(interpret)
    if hit is not None:
        return hit
    if _tracing():
        return False
    if not interpret:
        try:
            if jax.default_backend() != "tpu":
                _PROBE[interpret] = False
                return False
        except Exception:
            _PROBE[interpret] = False
            return False
    try:
        m = 8
        idx = (jnp.arange(64, dtype=jnp.int32) % m).reshape(2, 32)
        rho = jnp.full((2, 32), 1, jnp.int32)
        # lint-ok: trace-hazard: one-time backend availability probe —
        # it deliberately executes the kernel and inspects the result
        out = np.asarray(_scatter_max_call(idx, rho, m, interpret))
        # lint-ok: trace-hazard: probe verdict on host numpy, cached in
        # _PROBE for the process lifetime
        ok = out.shape == (2, m) and bool((out == 1).all())
    except Exception:
        ok = False
    _PROBE[interpret] = ok
    return ok


def enabled() -> bool:
    return bool(config.options().pallas_scatter) and available()


def impl_token() -> str:
    """Static plan fingerprint: which scatter backend a freshly traced
    plan would bake in. Rides the engine plan-cache key (and the
    vectorized HLL group token) so a flag flip retraces instead of
    aliasing a stale compile."""
    return "pallas" if enabled() else "xla"


def scatter_max(idx, rho, m: int):
    """Per-column scatter-max of ``rho`` into ``idx`` buckets:
    (C, B) i32 -> (C, m) i32, or ``None`` when the Pallas path is
    off/unavailable (caller falls back to the XLA scatter). idx must
    be in [0, m), rho in [0, 64) — the HLL builder guarantees both
    (idx is P hash bits, rho <= 33; masked rows map to (0, 0))."""
    if not enabled():
        return None
    return _scatter_max_call(idx, rho, m, _interpret_forced())


def _reset_probe_for_tests() -> None:
    _PROBE.clear()
    _make_call.cache_clear()
