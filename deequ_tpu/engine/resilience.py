"""Batch-level fault domains for the fused scan.

The reference inherits Spark's task-level fault tolerance for free:
a lost partition is recomputed from lineage and the aggregation plan
never notices (SURVEY.md §2.6). deequ_tpu drives its own scan loop, so
this module supplies the equivalent story at BATCH granularity — each
source batch is an independent fault domain:

- :class:`RetryPolicy` — configurable per-batch retry with exponential
  backoff and DETERMINISTIC jitter (seeded hash of (batch, attempt),
  never ``random``), plus an injectable ``sleep`` so tests run with
  zero wall-clock delay. ``config.scan_retry`` holds the active policy.
- transient-vs-deterministic taxonomy — IO/transfer errors
  (:class:`TransientScanError`, ``OSError`` and its timeout/connection
  subclasses) are retried; decode/shape errors are not (retrying a
  deterministic failure just burns the backoff budget). Allocation
  failures are a THIRD class: ``engine/memory.py``'s
  ``MemoryPressureError`` family is deliberately NOT transient
  (re-dispatching the same batch at the same size re-OOMs) — the scan
  loops route it to the adaptive batch backoff instead, and only its
  terminal ``BackoffExhausted`` form reaches the quarantine path here.
- :class:`ScanDegradation` — the provenance record a degraded scan
  carries: rows skipped, batches quarantined, error classes, one
  :class:`BatchFailure` per quarantined batch. Threaded through
  ``AnalyzerContext``/``VerificationResult``; checks map it to
  fail/warn/tolerate per ``config.degradation_policy``.
- :func:`resilient_batches` — the driver the engine's scan loops pull
  from: yields ``(index, item)``, re-creating the source iterator from
  the failing index on a transient error (generators die on raise, so
  sources expose ``start_batch``/``start_chunk``) and quarantining a
  batch that exhausts its attempts or fails deterministically.
- :class:`ScanKilled` — the fault harness's process-death stand-in.
  Derives from ``BaseException`` ON PURPOSE: the retry/quarantine
  machinery catches ``Exception`` only, so a kill unwinds the whole
  scan exactly like a real SIGKILL would, leaving any checkpoint as
  the only survivor.

Monoid states make all of this safe: a quarantined batch simply never
enters the fold, and collector ops (analyzers/spill.py) tolerate the
skip by construction — their dispatch counts unwritten buffer slots as
sentinels. See docs/RESILIENCE.md.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class TransientScanError(Exception):
    """An explicitly-transient source error (flaky IO, throttled reads,
    transfer hiccups). Raised by sources/wrappers that know the failure
    is worth retrying; the policy backs off and re-reads the batch."""


class BatchIntegrityError(Exception):
    """A batch arrived structurally wrong (short arrays, layout
    mismatch). Deterministic by definition — re-reading corrupt data
    yields corrupt data — so it quarantines immediately, never retries."""


class ScanKilled(BaseException):
    """Deterministic stand-in for process death (kill-at-batch-N in the
    fault harness). A ``BaseException`` so no ``except Exception`` in
    the retry/quarantine path can swallow it — the scan unwinds as if
    the process had died, and only a checkpoint survives."""


class ScanStalled(TransientScanError):
    """A batch exceeded the run's per-batch stall limit
    (``RunBudget.stall_s``) — raised by the deadline supervisor
    (``engine/deadline.py``) from whichever stage noticed: the
    streaming consumer's empty prefetch poll, the iterator's
    arrival-time check, or a blocked source released by the watchdog
    thread. A ``TransientScanError`` ON PURPOSE: a stall is retried
    (the read might succeed the second time) and quarantined when it
    keeps stalling — the exact PR 3 path, no new machinery."""


#: exception types the retry policy treats as transient. TimeoutError
#: and ConnectionError are OSError subclasses, listed for documentation.
TRANSIENT_ERROR_TYPES: Tuple[type, ...] = (
    TransientScanError,
    OSError,
    TimeoutError,
    ConnectionError,
)


def is_transient(exc: BaseException) -> bool:
    return isinstance(exc, TRANSIENT_ERROR_TYPES)


@dataclass(frozen=True)
class RetryPolicy:
    """Per-batch retry with exponential backoff and deterministic jitter.

    ``delay_s(batch_index, attempt)`` is a pure function of the policy
    and its arguments — the jitter comes from a seeded hash, never a
    global RNG — so a retried run is reproducible. ``sleep`` is
    injectable (tests pass a recorder; None means ``time.sleep``).
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    jitter: float = 0.25  # +/- fraction of the deterministic delay
    seed: int = 0
    sleep: Optional[Callable[[float], None]] = None

    def delay_s(self, batch_index: int, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based) of a batch."""
        base = min(
            self.backoff_base_s * self.backoff_multiplier ** (attempt - 1),
            self.backoff_max_s,
        )
        if not self.jitter:
            return base
        digest = hashlib.blake2b(
            f"{self.seed}:{batch_index}:{attempt}".encode(), digest_size=8
        ).digest()
        frac = int.from_bytes(digest, "big") / 2.0**64  # [0, 1)
        return base * (1.0 + self.jitter * (2.0 * frac - 1.0))

    def sleep_for(self, seconds: float) -> None:
        (self.sleep or time.sleep)(seconds)


@dataclass
class BatchFailure:
    """Provenance for ONE quarantined batch (error objects are reduced
    to strings so the record pickles into checkpoints and JSON)."""

    batch_index: int
    rows: int
    error_class: str
    message: str
    attempts: int

    def to_dict(self) -> Dict[str, Any]:
        return {
            "batch_index": self.batch_index,
            "rows": self.rows,
            "error_class": self.error_class,
            "message": self.message,
            "attempts": self.attempts,
        }


@dataclass
class ScanDegradation:
    """What a degraded scan lost, and why — attached to every run whose
    fused scan quarantined at least one batch. ``rows_skipped`` is the
    exact unpadded row count of the quarantined batches, so consumers
    can bound the metric error (skipped/total rows)."""

    batches_quarantined: int = 0
    rows_skipped: int = 0
    retries: int = 0
    failures: List[BatchFailure] = field(default_factory=list)

    @property
    def is_degraded(self) -> bool:
        return self.batches_quarantined > 0

    @property
    def error_classes(self) -> List[str]:
        return sorted({f.error_class for f in self.failures})

    def record_quarantine(
        self, batch_index: int, rows: int, exc: BaseException, attempts: int
    ) -> None:
        from deequ_tpu.telemetry import get_telemetry

        self.batches_quarantined += 1
        self.rows_skipped += int(rows)
        self.failures.append(
            BatchFailure(
                batch_index=int(batch_index),
                rows=int(rows),
                error_class=type(exc).__name__,
                message=str(exc)[:500],
                attempts=int(attempts),
            )
        )
        tm = get_telemetry()
        tm.counter("engine.batches_quarantined").inc()
        tm.event(
            "batch_quarantined",
            batch_index=int(batch_index),
            rows=int(rows),
            error_class=type(exc).__name__,
            attempts=int(attempts),
        )

    def record_retry(self) -> None:
        from deequ_tpu.telemetry import get_telemetry

        self.retries += 1
        get_telemetry().counter("engine.batch_retries").inc()

    def merge(self, other: Optional["ScanDegradation"]) -> "ScanDegradation":
        if other is None:
            return self
        return ScanDegradation(
            batches_quarantined=(
                self.batches_quarantined + other.batches_quarantined
            ),
            rows_skipped=self.rows_skipped + other.rows_skipped,
            retries=self.retries + other.retries,
            failures=self.failures + other.failures,
        )

    @staticmethod
    def merge_optional(
        a: Optional["ScanDegradation"], b: Optional["ScanDegradation"]
    ) -> Optional["ScanDegradation"]:
        if a is None:
            return b
        return a.merge(b)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "batches_quarantined": self.batches_quarantined,
            "rows_skipped": self.rows_skipped,
            "retries": self.retries,
            "error_classes": self.error_classes,
            "failures": [f.to_dict() for f in self.failures],
        }


def retry_transient(
    fn: Callable[[], Any],
    policy: RetryPolicy,
    batch_index: int,
    degradation: ScanDegradation,
):
    """Run ``fn`` retrying TRANSIENT failures per the policy (used for
    the in-loop transfer stage, where no iterator restart is needed).
    Deterministic errors and exhaustion re-raise — the caller decides
    whether that means quarantine or abort."""
    attempts = 0
    while True:
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classified below
            attempts += 1
            if is_transient(exc) and attempts < policy.max_attempts:
                degradation.record_retry()
                policy.sleep_for(policy.delay_s(batch_index, attempts))
                continue
            raise


def resilient_batches(
    make_iter: Callable[[int], Iterator[Any]],
    policy: RetryPolicy,
    degradation: ScanDegradation,
    rows_for: Callable[[int], int],
    start: int = 0,
    validate: Optional[Callable[[Any], None]] = None,
) -> Iterator[Tuple[int, Any]]:
    """Yield ``(index, item)`` from ``make_iter(start_index)`` with
    per-item fault domains.

    A raising generator is DEAD (PEP 342), so retry means re-creating
    the source iterator from the failing index — ``make_iter`` is a
    factory over a start index, which the data layer supports via
    ``start_batch``/``start_chunk``. Failure handling:

    - transient error, attempts remain: back off (deterministic delay,
      injectable sleep), restart from the same index;
    - transient exhaustion or deterministic error: quarantine the item
      (recorded on ``degradation`` with its exact unpadded row count),
      restart from the next index;
    - ``validate(item)`` raising: deterministic corruption — quarantine
      WITHOUT an iterator restart (the source itself is still good);
    - ``ScanKilled``/``BaseException``: never caught here — unwinds the
      scan like real process death.

    The failing index is always ``start + items_already_yielded``: the
    prefetcher's bounded queue is FIFO, so even an error raised on the
    prefetch thread surfaces in source order.
    """
    index = start
    attempts = 0
    it = make_iter(index)
    while True:
        try:
            item = next(it)
        except StopIteration:
            return
        except Exception as exc:  # noqa: BLE001 — classified below
            attempts += 1
            if is_transient(exc) and attempts < policy.max_attempts:
                degradation.record_retry()
                policy.sleep_for(policy.delay_s(index, attempts))
                it = make_iter(index)
                continue
            degradation.record_quarantine(
                index, rows_for(index), exc, attempts
            )
            attempts = 0
            index += 1
            it = make_iter(index)
            continue
        if validate is not None:
            try:
                validate(item)
            except Exception as exc:  # noqa: BLE001 — corruption path
                degradation.record_quarantine(index, rows_for(index), exc, 1)
                attempts = 0
                index += 1
                continue
        attempts = 0
        yield index, item
        index += 1
