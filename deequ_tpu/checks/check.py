"""The Check fluent DSL: declarative data-quality constraints.

Reference: ``src/main/scala/com/amazon/deequ/checks/Check.scala``
(SURVEY.md §2.5) — ~40 fluent methods each appending a ``Constraint``;
``required_analyzers()`` is how the runner learns what to compute; checks
are immutable (every method returns a new Check). ``where``-filterable
methods return a :class:`CheckWithLastConstraintFilterable` exactly like
the reference's ``CheckWithLastConstraintFilterable``.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, List, Optional, Sequence, Union

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.basic import (
    ColumnCount,
    Completeness,
    Compliance,
    Correlation,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.datatype import DataType
from deequ_tpu.analyzers.grouping import (
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    MutualInformation,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.constraints.constraint import (
    AnalysisBasedConstraint,
    Constraint,
    ConstraintResult,
    ConstraintStatus,
    NamedConstraint,
)

Assertion = Callable[[Any], bool]


def is_one(value: float) -> bool:
    return value == 1.0


class CheckLevel(enum.Enum):
    ERROR = "Error"
    WARNING = "Warning"


class CheckStatus(enum.Enum):
    SUCCESS = "Success"
    WARNING = "Warning"
    ERROR = "Error"


class CheckResult:
    def __init__(
        self,
        check: "Check",
        status: CheckStatus,
        constraint_results: List[ConstraintResult],
    ):
        self.check = check
        self.status = status
        self.constraint_results = constraint_results


# Patterns (reference: Check.scala's containsEmail/URL/SSN/CreditCardNumber)
PATTERN_EMAIL = r"^[a-zA-Z0-9.!#$%&'*+/=?^_`{|}~-]+@[a-zA-Z0-9-]+(?:\.[a-zA-Z0-9-]+)*$"
PATTERN_URL = r"^(https?|ftp)://[^\s/$.?#].[^\s]*$"
PATTERN_SSN = r"^(?!000|666|9\d{2})\d{3}-(?!00)\d{2}-(?!0000)\d{4}$"
PATTERN_CREDITCARD = (
    r"^(4\d{12}(?:\d{3})?|(?:5[1-5]\d{2}|222[1-9]|22[3-9]\d|2[3-6]\d{2}"
    r"|27[01]\d|2720)\d{12}|3[47]\d{13}|6(?:011|5\d{2})\d{12}"
    r"|3(?:0[0-5]|[68]\d)\d{11})$"
)


class ConstrainableDataTypes(enum.Enum):
    NULL = "Unknown"
    FRACTIONAL = "Fractional"
    INTEGRAL = "Integral"
    BOOLEAN = "Boolean"
    STRING = "String"
    NUMERIC = "Numeric"  # Fractional + Integral


class Check:
    """An immutable group of constraints at one severity level."""

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: Optional[List[Constraint]] = None,
    ):
        self.level = level
        self.description = description
        self.constraints: List[Constraint] = list(constraints or [])

    # -- plumbing -------------------------------------------------------

    def add_constraint(self, constraint: Constraint) -> "Check":
        return Check(
            self.level, self.description, self.constraints + [constraint]
        )

    def _add_filterable(
        self, creation_fn: Callable[[Optional[str]], Constraint]
    ) -> "CheckWithLastConstraintFilterable":
        return CheckWithLastConstraintFilterable(
            self.level, self.description, self.constraints, creation_fn
        )

    def required_analyzers(self) -> List[Analyzer]:
        out: List[Analyzer] = []
        for c in self.constraints:
            inner = c.inner if hasattr(c, "inner") else c
            analyzer = getattr(inner, "analyzer", None)
            if analyzer is not None:
                out.append(analyzer)
        return out

    def evaluate(self, context) -> CheckResult:
        results = [c.evaluate(context) for c in self.constraints]
        if all(r.status == ConstraintStatus.SUCCESS for r in results):
            status = CheckStatus.SUCCESS
        elif self.level == CheckLevel.ERROR:
            status = CheckStatus.ERROR
        else:
            status = CheckStatus.WARNING
        return CheckResult(self, status, results)

    # -- size / schema --------------------------------------------------

    def has_size(
        self, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Size(where=where), assertion, hint=hint
            )
        )

    def has_column_count(
        self, assertion: Assertion, hint: Optional[str] = None
    ) -> "Check":
        return self.add_constraint(
            AnalysisBasedConstraint(ColumnCount(), assertion, hint=hint)
        )

    # -- completeness ---------------------------------------------------

    def is_complete(
        self, column: str, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: NamedConstraint(
                AnalysisBasedConstraint(
                    Completeness(column, where), is_one, hint=hint
                ),
                f"CompletenessConstraint({column})",
            )
        )

    def has_completeness(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Completeness(column, where), assertion, hint=hint
            )
        )

    def are_complete(
        self, columns: Sequence[str], hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        predicate = " AND ".join(f"{c} IS NOT NULL" for c in columns)
        name = f"Combined Completeness of {','.join(columns)}"
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Compliance(name, predicate, where), is_one, hint=hint
            )
        )

    def have_completeness(
        self,
        columns: Sequence[str],
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        predicate = " AND ".join(f"{c} IS NOT NULL" for c in columns)
        name = f"Combined Completeness of {','.join(columns)}"
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Compliance(name, predicate, where), assertion, hint=hint
            )
        )

    def are_any_complete(
        self, columns: Sequence[str], hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        predicate = " OR ".join(f"{c} IS NOT NULL" for c in columns)
        name = f"Any Completeness of {','.join(columns)}"
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Compliance(name, predicate, where), is_one, hint=hint
            )
        )

    # -- uniqueness family ----------------------------------------------

    def is_unique(
        self, column: str, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: NamedConstraint(
                AnalysisBasedConstraint(
                    Uniqueness(column, where), is_one, hint=hint
                ),
                f"UniquenessConstraint({column})",
            )
        )

    def is_primary_key(
        self, column: str, *other_columns: str, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        columns = (column,) + other_columns
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Uniqueness(columns, where), is_one, hint=hint
            )
        )

    def has_uniqueness(
        self,
        columns: Union[str, Sequence[str]],
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Uniqueness(columns, where), assertion, hint=hint
            )
        )

    def has_distinctness(
        self,
        columns: Union[str, Sequence[str]],
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Distinctness(columns, where), assertion, hint=hint
            )
        )

    def has_unique_value_ratio(
        self,
        columns: Union[str, Sequence[str]],
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                UniqueValueRatio(columns, where), assertion, hint=hint
            )
        )

    def has_number_of_distinct_values(
        self,
        column: str,
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                CountDistinct(column, where), assertion, hint=hint
            )
        )

    # -- distribution ---------------------------------------------------

    def has_histogram_values(
        self,
        column: str,
        assertion: Callable[[Any], bool],
        max_bins: int = 1000,
        hint: Optional[str] = None,
    ) -> "Check":
        return self.add_constraint(
            AnalysisBasedConstraint(
                Histogram(column, max_detail_bins=max_bins),
                assertion,
                hint=hint,
            )
        )

    def has_entropy(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Entropy(column, where), assertion, hint=hint
            )
        )

    def has_mutual_information(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                MutualInformation((column_a, column_b), where),
                assertion,
                hint=hint,
            )
        )

    # -- sketches -------------------------------------------------------

    def has_approx_count_distinct(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers.hll import ApproxCountDistinct

        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                ApproxCountDistinct(column, where), assertion, hint=hint
            )
        )

    def has_approx_quantile(
        self,
        column: str,
        quantile: float,
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers.kll import ApproxQuantile

        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                ApproxQuantile(column, quantile, where=where),
                assertion,
                hint=hint,
            )
        )

    def kll_sketch_satisfies(
        self,
        column: str,
        assertion: Callable[[Any], bool],
        kll_parameters=None,
        hint: Optional[str] = None,
    ) -> "Check":
        from deequ_tpu.analyzers.kll import KLLSketch

        analyzer = (
            KLLSketch(column, kll_parameters)
            if kll_parameters is not None
            else KLLSketch(column)
        )
        return self.add_constraint(
            AnalysisBasedConstraint(analyzer, assertion, hint=hint)
        )

    # -- numeric stats --------------------------------------------------

    def has_min(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Minimum(column, where), assertion, hint=hint
            )
        )

    def has_max(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Maximum(column, where), assertion, hint=hint
            )
        )

    def has_mean(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Mean(column, where), assertion, hint=hint
            )
        )

    def has_sum(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Sum(column, where), assertion, hint=hint
            )
        )

    def has_standard_deviation(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                StandardDeviation(column, where), assertion, hint=hint
            )
        )

    def has_min_length(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                MinLength(column, where), assertion, hint=hint
            )
        )

    def has_max_length(
        self, column: str, assertion: Assertion, hint: Optional[str] = None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                MaxLength(column, where), assertion, hint=hint
            )
        )

    def has_correlation(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Correlation(column_a, column_b, where), assertion, hint=hint
            )
        )

    # -- predicates -----------------------------------------------------

    def satisfies(
        self,
        column_condition: str,
        constraint_name: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                Compliance(constraint_name, column_condition, where),
                assertion,
                hint=hint,
            )
        )

    def has_pattern(
        self,
        column: str,
        pattern: str,
        assertion: Assertion = is_one,
        name: Optional[str] = None,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        def create(where: Optional[str]) -> Constraint:
            constraint: Constraint = AnalysisBasedConstraint(
                PatternMatch(column, pattern, where), assertion, hint=hint
            )
            if name:
                constraint = NamedConstraint(constraint, name)
            return constraint

        return self._add_filterable(create)

    def contains_credit_card_number(
        self, column: str, assertion: Assertion = is_one
    ) -> "CheckWithLastConstraintFilterable":
        return self.has_pattern(
            column,
            PATTERN_CREDITCARD,
            assertion,
            name=f"containsCreditCardNumber({column})",
        )

    def contains_email(
        self, column: str, assertion: Assertion = is_one
    ) -> "CheckWithLastConstraintFilterable":
        return self.has_pattern(
            column, PATTERN_EMAIL, assertion, name=f"containsEmail({column})"
        )

    def contains_url(
        self, column: str, assertion: Assertion = is_one
    ) -> "CheckWithLastConstraintFilterable":
        return self.has_pattern(
            column, PATTERN_URL, assertion, name=f"containsURL({column})"
        )

    def contains_ssn(
        self, column: str, assertion: Assertion = is_one
    ) -> "CheckWithLastConstraintFilterable":
        return self.has_pattern(
            column, PATTERN_SSN, assertion, name=f"containsSSN({column})"
        )

    def has_data_type(
        self,
        column: str,
        data_type: ConstrainableDataTypes,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        def picker(distribution) -> float:
            total = sum(v.absolute for v in distribution.values.values())
            if total == 0:
                return 0.0
            if data_type == ConstrainableDataTypes.NUMERIC:
                hits = (
                    distribution.values["Fractional"].absolute
                    + distribution.values["Integral"].absolute
                )
            else:
                hits = distribution.values[data_type.value].absolute
            return hits / total

        return self._add_filterable(
            lambda where: AnalysisBasedConstraint(
                DataType(column, where), assertion, value_picker=picker,
                hint=hint,
            )
        )

    # -- sign / range ---------------------------------------------------

    def is_non_negative(
        self,
        column: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        # nulls are compliant, matching the reference's COALESCE(col, 0) >= 0
        return self.satisfies(
            f"{column} IS NULL OR {column} >= 0",
            f"{column} is non-negative",
            assertion,
            hint=hint,
        )

    def is_positive(
        self,
        column: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"{column} IS NULL OR {column} > 0",
            f"{column} is positive",
            assertion,
            hint=hint,
        )

    def is_less_than(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"{column_a} < {column_b}",
            f"{column_a} is less than {column_b}",
            assertion,
            hint=hint,
        )

    def is_less_than_or_equal_to(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"{column_a} <= {column_b}",
            f"{column_a} is less than or equal to {column_b}",
            assertion,
            hint=hint,
        )

    def is_greater_than(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"{column_a} > {column_b}",
            f"{column_a} is greater than {column_b}",
            assertion,
            hint=hint,
        )

    def is_greater_than_or_equal_to(
        self,
        column_a: str,
        column_b: str,
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"{column_a} >= {column_b}",
            f"{column_a} is greater than or equal to {column_b}",
            assertion,
            hint=hint,
        )

    def is_contained_in(
        self,
        column: str,
        allowed_values: Sequence[Union[str, float]],
        assertion: Assertion = is_one,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        quoted = ", ".join(
            "'" + v.replace("'", "\\'") + "'" if isinstance(v, str) else str(v)
            for v in allowed_values
        )
        predicate = f"{column} IS NULL OR {column} IN ({quoted})"
        return self.satisfies(
            predicate,
            f"{column} contained in {','.join(str(v) for v in allowed_values)}",
            assertion,
            hint=hint,
        )

    def is_in_range(
        self,
        column: str,
        lower: float,
        upper: float,
        include_lower: bool = True,
        include_upper: bool = True,
        hint: Optional[str] = None,
    ) -> "CheckWithLastConstraintFilterable":
        lo_op = ">=" if include_lower else ">"
        hi_op = "<=" if include_upper else "<"
        predicate = (
            f"{column} IS NULL OR ({column} {lo_op} {lower} AND "
            f"{column} {hi_op} {upper})"
        )
        return self.satisfies(
            predicate,
            f"{column} between {lower} and {upper}",
            is_one,
            hint=hint,
        )


class CheckWithLastConstraintFilterable(Check):
    """A Check whose most recent constraint accepts a ``.where`` filter
    (reference: CheckWithLastConstraintFilterable in Check.scala)."""

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: List[Constraint],
        creation_fn: Callable[[Optional[str]], Constraint],
    ):
        super().__init__(
            level, description, constraints + [creation_fn(None)]
        )
        self._base_constraints = list(constraints)
        self._creation_fn = creation_fn

    def where(self, filter_condition: str) -> Check:
        return Check(
            self.level,
            self.description,
            self._base_constraints
            + [self._creation_fn(filter_condition)],
        )
