"""Device-side high-cardinality grouping: sort + segment counting.

Reference context: the reference's grouping analyzers run a cluster
shuffle (``groupBy().count()``, SURVEY.md §2.6); deequ_tpu's dense
scatter-add path (analyzers/grouping.py) covers key spaces that fit a
device count vector, and historically spilled anything larger to the
host CPU's Arrow ``group_by`` — the one remaining Spark-job-shaped hole
in the engine (SURVEY.md §7 hard part #1; VERDICT r2 missing #1).

This module closes it for the common shape — ONE high-cardinality
numeric grouping column (an id/key column under CountDistinct /
Uniqueness / Distinctness / Entropy / Histogram): the TPU-native
equivalent of the shuffle is a device **sort + segment-boundary count**.

The sort uses a SINGLE u64 key lane — TPU sort compile time scales
brutally with operand count (measured on v5e: 1-operand ~25s,
3-operand 60-135s, both nearly flat in array length), so instead of
carrying drop/null flags as extra sort keys:

- int keys are XOR-biased into u64 (order-preserving, reversible);
  rejected rows (padding, where-filter, nulls) map to the u64 sentinel
  ``0xFFFF...`` and their EXACT count is kept as a scalar — after
  counting, the sentinel-sharing segment is corrected by subtracting
  that scalar, so even an int64.max key stays exact;
- float32 keys are their RAW BITS (``bitcast f32->u32``, the one
  bitcast width TPUs lower) widened to u64 — bit-grouping matches
  Arrow's dictionary semantics exactly (-0.0 != +0.0; NaN payloads
  canonicalized so NaN == NaN) and can never reach the sentinel;
- float64 keys bitcast to u64 directly — only on backends whose X64
  rewriter lowers 64-bit bitcasts (CPU); on TPU, f64 grouping columns
  keep the host Arrow fallback (TPU demotes f64 anyway, so a device
  path could not be bit-exact there);
- the null group (Histogram's ``include_nulls``) is a separate scalar
  count, re-inserted host-side — it never needs a key lane at all.

Sorting by bits rather than value order is fine: grouping only needs
EQUAL keys adjacent, and bit-equality is the grouping relation itself.

Count-shaped metrics then finalize from ON-DEVICE scalars (#groups,
#count==1, entropy, #rows) — a 10M-group state never crosses the
tunnel; Histogram fetches only its top-K bins via ``lax.top_k``. The
full (keys, counts) arrays stay device-resident and are fetched lazily
only if something actually needs the values (persistence, incremental
merge).

No dictionary is built: unlike the dense path (host Arrow
dictionary_encode) the keys here are the column's own 64-bit values, so
a 1B-row id column never materializes a host-side distinct set at all.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind, ROW_MASK

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)
_BIAS = np.uint64(1) << np.uint64(63)


@functools.lru_cache(maxsize=None)
def _chunk_key_fn(key_kind: str, include_nulls: bool):
    """Jitted: one scan chunk -> (flat u64 keys with sentinel for
    non-contributing rows, #sentinel rows, #null rows kept).
    ``key_kind``: "int" | "f32" | "f64" (see module docstring)."""

    def build(values, mask, rows):
        if key_kind == "f32":
            x = values.astype(jnp.float32)
            bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
            # canonical NaN bits: Arrow dictionary_encode groups NaN==NaN
            bits = jnp.where(
                jnp.isnan(x), jnp.uint32(0x7FC00000), bits
            )
            keys = bits.astype(jnp.uint64)
        elif key_kind == "f64":
            x = values.astype(jnp.float64)
            bits = jax.lax.bitcast_convert_type(x, jnp.uint64)
            keys = jnp.where(
                jnp.isnan(x),
                jnp.uint64(0x7FF8000000000000),
                bits,
            )
        else:
            keys = values.astype(jnp.int64).astype(jnp.uint64) ^ _BIAS
        if include_nulls:
            null = rows & ~mask
            contributes = rows & mask
        else:
            null = jnp.zeros_like(rows)
            contributes = rows & mask
        keys = jnp.where(contributes, keys, _SENTINEL)
        n_sentinel = jnp.sum(~contributes, dtype=jnp.int64)
        n_null = jnp.sum(null, dtype=jnp.int64)
        return keys.ravel(), n_sentinel, n_null

    return jax.jit(build)


@functools.lru_cache(maxsize=None)
def _finalize_fn():
    """Jitted: flat u64 keys + sentinel count -> per-group arrays and
    scalars. Output arrays have length N+1 (slot N absorbs non-boundary
    scatter writes); value groups occupy slots [0, num_segments) with
    the sentinel-sharing segment's count corrected (possibly to 0).
    Counts are i32 (a chip processes < 2^31 rows per state; cross-state
    merges widen)."""

    def run(keys, n_sentinel):
        n = keys.shape[0]
        k = jnp.sort(keys)  # ONE sort operand: see module docstring
        boundary = jnp.concatenate(
            [jnp.ones(1, dtype=bool), k[1:] != k[:-1]]
        )
        seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        num_segments = seg[-1] + 1
        counts = jnp.zeros(n + 1, dtype=jnp.int32).at[seg].add(1)
        # sentinel correction: all non-contributing rows sorted to the
        # end and share the last segment with any legit int64.max rows
        has_sentinel = k[-1] == _SENTINEL
        counts = counts.at[seg[-1]].add(
            -jnp.where(has_sentinel, n_sentinel, 0).astype(jnp.int32)
        )
        group_keys = (
            jnp.zeros(n + 1, dtype=keys.dtype)
            .at[jnp.where(boundary, seg, n)]
            .set(k)
        )
        in_range = jnp.arange(n + 1, dtype=jnp.int32) < num_segments
        gmask = in_range & (counts > 0)
        num_groups = jnp.sum(gmask, dtype=jnp.int64)
        total = (n - n_sentinel).astype(jnp.int64)
        unique = jnp.sum((counts == 1) & gmask, dtype=jnp.int64)
        # entropy over value groups (all non-null by construction)
        c = jnp.where(gmask, counts, 0).astype(jnp.float64)
        tot_f = jnp.maximum(total, 1).astype(jnp.float64)
        p = c / tot_f
        entropy = -jnp.sum(jnp.where(c > 0, p * jnp.log(p), 0.0))
        scalars = {
            "num_segments": num_segments.astype(jnp.int64),
            "num_groups": num_groups,
            "total": total,
            "unique": unique,
            "entropy": entropy,
        }
        return scalars, group_keys, counts

    return jax.jit(run)


@functools.partial(jax.jit, static_argnums=(3,))
def _topk_fn(counts, group_keys, num_segments, k):
    in_range = (
        jnp.arange(counts.shape[0], dtype=jnp.int32) < num_segments
    )
    tc, ti = jax.lax.top_k(jnp.where(in_range, counts, -1), k)
    return tc, jnp.take(group_keys, ti)


class DeviceFrequencies(FrequenciesAndNumRows):
    """FrequenciesAndNumRows whose groups live ON DEVICE.

    Count metrics read precomputed scalars; ``keys``/``counts`` fetch
    and decode lazily (only persistence, incremental merge, and
    MutualInformation ever need the values). The null group, if any, is
    a host scalar appended on access."""

    def __init__(
        self,
        columns: Tuple[str, ...],
        values_dtype: np.dtype,
        scalars: Dict[str, object],
        group_keys,
        counts,
        null_rows: int,
        include_nulls: bool,
    ):
        self.columns = tuple(columns)
        self._values_dtype = np.dtype(values_dtype)
        self._is_float = self._values_dtype.kind == "f"
        self._num_segments = int(scalars["num_segments"])
        self._value_groups = int(scalars["num_groups"])
        self._unique = int(scalars["unique"])
        self._entropy = float(scalars["entropy"])
        self._null_rows = int(null_rows) if include_nulls else 0
        self._include_nulls = include_nulls
        self.num_rows = int(scalars["total"]) + self._null_rows
        self._dev = (group_keys, counts)
        self._keys_host: Optional[np.ndarray] = None
        self._counts_host: Optional[np.ndarray] = None

    # -- FrequenciesAndNumRows surface ---------------------------------

    @property
    def _has_null_group(self) -> bool:
        return self._null_rows > 0

    @property
    def num_groups(self) -> int:
        return self._value_groups + (1 if self._has_null_group else 0)

    def _fetch(self) -> None:
        if self._counts_host is None:
            from deequ_tpu.engine.pack import packed_device_get

            gk, c = packed_device_get(self._dev)
            s = self._num_segments
            raw_keys = np.asarray(gk)[:s]
            raw_counts = np.asarray(c)[:s]
            live = raw_counts > 0  # drops a zeroed sentinel segment
            self._keys_host = raw_keys[live]
            self._counts_host = raw_counts[live].astype(np.int64)

    def _decode_keys(self, raw: np.ndarray) -> np.ndarray:
        """(K,) raw u64 keys -> (K,) object values in the column's OWN
        dtype — a float32 column's keys must decode to np.float32, or
        Histogram labels and persisted keys would diverge from the
        dense dictionary path (str(np.float64(1.1)) !=
        str(np.float32(1.1))). Float keys are raw bits; ints unbias."""
        if self._values_dtype == np.float32:
            vals = raw.astype(np.uint32).view(np.float32)
        elif self._values_dtype == np.float64:
            vals = raw.view(np.float64)
        elif self._is_float:  # f16 materialized as f32 on the wire
            vals = raw.astype(np.uint32).view(np.float32).astype(
                self._values_dtype
            )
        else:
            vals = (raw ^ _BIAS).view(np.int64)
        return vals.astype(object)

    @property
    def counts(self) -> np.ndarray:
        self._fetch()
        if self._has_null_group:
            return np.concatenate(
                [self._counts_host, [np.int64(self._null_rows)]]
            )
        return self._counts_host

    @property
    def keys(self) -> np.ndarray:
        self._fetch()
        n = self.num_groups
        out = np.empty((n, 1), dtype=object)
        out[: len(self._keys_host), 0] = self._decode_keys(self._keys_host)
        if self._has_null_group:
            out[-1, 0] = None
        return out

    def non_null_group_mask(self) -> np.ndarray:
        mask = np.ones(self.num_groups, dtype=bool)
        if self._has_null_group:
            mask[-1] = False
        return mask

    # -- fast paths (no device->host group transfer) -------------------

    def count_unique_groups(self) -> int:
        return self._unique + (1 if self._null_rows == 1 else 0)

    def entropy_nats(self) -> float:
        from deequ_tpu.analyzers.base import EmptyStateException

        if self.num_rows - self._null_rows == 0:
            raise EmptyStateException("Entropy over empty distribution.")
        return self._entropy

    def top_groups(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        gk, c = self._dev
        kk = min(k, self._num_segments)
        pairs = []
        if kk > 0:
            from deequ_tpu.engine.pack import packed_device_get

            tc, tkeys = packed_device_get(
                _topk_fn(c, gk, np.int32(self._num_segments), kk)
            )
            tc = np.asarray(tc)
            live = tc > 0  # zeroed sentinel segment never bins
            decoded = self._decode_keys(np.asarray(tkeys)[live])
            pairs = list(zip(decoded, tc[live].astype(np.int64)))
        if self._has_null_group:
            pairs.append((None, np.int64(self._null_rows)))
            pairs.sort(key=lambda kv: -kv[1])
            pairs = pairs[:k]
        if not pairs:
            return np.zeros(0, dtype=object), np.zeros(0, dtype=np.int64)
        keys_out = np.empty(len(pairs), dtype=object)
        keys_out[:] = [p[0] for p in pairs]
        return keys_out, np.asarray([p[1] for p in pairs], dtype=np.int64)


def device_spill_eligible(dataset: Dataset, plan, engine=None) -> bool:
    """True when a frequency plan should run the device sort path:
    a single INTEGRAL/FRACTIONAL grouping column whose flat sort fits
    the device budget. Strings keep the dense/Arrow path (their keys
    are dictionary codes); booleans and timestamps keep it too so
    decoded key VALUES (True/False, datetime64) stay merge-compatible
    with dense-path states; uint64 can't widen to the i64 key lane.

    Note the asymmetry with the dense path: dense must first build a
    host-side dictionary (an Arrow hash pass over every row) just to
    LEARN the cardinality; the sort path needs no dictionary at all,
    so for numeric columns it wins even at low cardinality."""
    from deequ_tpu import config

    opts = config.options()
    if not opts.device_spill_grouping:
        return False
    if not opts.device_cache_bytes:
        return False  # chunked device path needs the resident cache
    if engine is not None and engine.mesh is not None:
        return False  # sharded sort needs an all_to_all re-shard (TODO)
    if opts.engine == "cpu":
        return False  # honor the engine-selection flag's placement
    if dataset.num_rows >= 2**31:
        return False  # i32 segment counts; the dense path widens, we gate
    if len(plan.columns) != 1:
        return False
    column = plan.columns[0]
    kind = dataset.schema.kind_of(column)
    if kind not in (Kind.INTEGRAL, Kind.FRACTIONAL):
        return False
    try:
        dt = dataset.request_dtype(ColumnRequest(column, "values"))
    except Exception:  # noqa: BLE001 — odd column: use the host path
        return False
    if dt.kind == "u" and dt.itemsize == 8:
        return False
    if dt.kind == "f" and np.dtype(dt).itemsize == 8:
        # f64 keys need a 64-bit bitcast, which only CPU-class backends
        # lower (TPU's X64 rewriter has no u64 bitcast and demotes f64
        # anyway); f64 grouping columns keep the host Arrow path there
        import jax

        if jax.default_backend() != "cpu":
            return False
    # headroom gate: the pass pins values+mask chunks in the cache
    # (~9 B/row) AND allocates sort transients outside cache accounting
    # (u64 keys + sorted copy + group keys + counts ~ 30 B/row, pow2
    # padded); 64 B/row keeps the whole pass clear of HBM even when the
    # budget is sized close to the device memory
    return dataset.num_rows * 64 <= opts.device_cache_bytes


def device_spill_frequencies(
    dataset: Dataset, plan, engine
) -> "DeviceFrequencies":
    """One high-cardinality frequency pass fully on device."""
    from deequ_tpu import config
    from deequ_tpu.engine.scan import CHUNK_BATCHES
    from deequ_tpu.sql.predicate import compile_predicate

    column = plan.columns[0]
    values_dtype = dataset.request_dtype(ColumnRequest(column, "values"))
    if values_dtype.kind != "f":
        key_kind = "int"
    elif np.dtype(values_dtype).itemsize == 8:
        key_kind = "f64"
    else:
        key_kind = "f32"
    requests = [
        ColumnRequest(column, "values"),
        ColumnRequest(column, "mask"),
    ]
    pred = None
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        requests += list(pred.requests)

    batch_size = engine._resolve_batch_size(dataset.num_rows)
    nb = dataset.num_batches(batch_size)
    chunk_batches = min(CHUNK_BATCHES, nb)
    key_fn = _chunk_key_fn(key_kind, bool(plan.include_nulls))

    keys_parts = []
    n_sentinel = jnp.int64(0)
    n_null = jnp.int64(0)
    for chunk in dataset.device_scan_chunks(
        requests,
        batch_size,
        chunk_batches=chunk_batches,
        budget_bytes=config.options().device_cache_bytes,
    ):
        rows = chunk[ROW_MASK]
        if pred is not None:
            flat = {k: v.reshape(-1) for k, v in chunk.items()}
            rows = rows & pred.complies(flat).reshape(rows.shape)
        k, ns, nn = key_fn(
            chunk[f"{column}::values"], chunk[f"{column}::mask"], rows
        )
        keys_parts.append(k)
        n_sentinel = n_sentinel + ns
        n_null = n_null + nn

    keys = (
        jnp.concatenate(keys_parts) if len(keys_parts) > 1 else keys_parts[0]
    )
    # pad to pow2 so the (expensive-to-compile) sort program is shared
    # across datasets whose row counts round the same way
    n = keys.shape[0]
    padded = 1 << max(1, int(n - 1).bit_length()) if n > 1 else 1
    if padded != n:
        keys = jnp.concatenate(
            [keys, jnp.full(padded - n, _SENTINEL, dtype=keys.dtype)]
        )
        n_sentinel = n_sentinel + (padded - n)

    scalars, group_keys, counts = _finalize_fn()(keys, n_sentinel)
    from deequ_tpu.engine.pack import packed_device_get

    fetched = packed_device_get((scalars, n_null))
    scalars, n_null_host = fetched
    return DeviceFrequencies(
        plan.columns,
        values_dtype,
        scalars,
        group_keys,
        counts,
        int(n_null_host),
        bool(plan.include_nulls),
    )
