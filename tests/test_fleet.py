"""Fleet failover (docs/SERVICE.md "Fleet failover"): heartbeat
leases, orphan-run adoption, and epoch fencing for the distributed
service (service/fleet.py + the service/scheduler/subproc wiring).

Three layers of evidence here:

- ``FleetSupervisor`` units on a ``ManualClock``: lease-chain
  registration, heartbeat renewal, staleness-driven adoption, the
  CAS exactly-one-adopter guarantee, chain GC, retirement, the chain
  prefix-collision trap, and the fleet poison ledger.
- In-process two-replica services: a zombie replica (its chain
  adopted while it was paused) must refuse admission with
  ``FencedReplica`` and silently drop every journal/repository
  persist — ZERO bytes of journal growth, zero repository saves —
  while the adopter re-admits its pending runs exactly once.
- The chaos differential: a whole replica process (service + fleet
  supervisor + a mid-scan run with durable checkpoints) dies by REAL
  SIGKILL; a surviving replica adopts its journal within one poll of
  lease expiry, resumes the run from the shared durable cursor, and
  finishes BIT-IDENTICAL to an uninterrupted oracle run.

Child functions are module-level (spawn pickles by reference); the
autouse reap fixture asserts no zombie children leak.
"""

import json
import multiprocessing
import os
import signal
import time

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine.deadline import ManualClock
from deequ_tpu.engine.subproc import (
    CHILD_EPOCH_ENV,
    CrashLoopError,
    IsolatedRunner,
    child_epoch_fenced,
    reset_breakers,
)
from deequ_tpu.service import (
    Priority,
    RunRequest,
    RunState,
    VerificationService,
)
from deequ_tpu.service import service as service_module
from deequ_tpu.service.fleet import (
    FencedReplica,
    FleetSupervisor,
    Lease,
    _lease_key,
    epoch_fence_check,
)
from deequ_tpu.service.journal import RunJournal
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.verification.suite import VerificationSuite


@pytest.fixture(autouse=True)
def _reaped_and_reset():
    reset_breakers()
    yield
    assert multiprocessing.active_children() == []
    reset_breakers()


def _table_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.normal(size=n).tolist(),
        "g": (np.arange(n) % 7).tolist(),
    }


def _checks(n=1000):
    return [
        Check(CheckLevel.ERROR, "fleet-failover")
        .has_size(lambda s, n=n: s == n)
        .is_complete("a")
    ]


def _result_values(result):
    out = []
    for analyzer, metric in result.metrics.items():
        assert metric.value.is_success, (analyzer, metric.value)
        out.append((str(analyzer), metric.value.get()))
    return sorted(out)


def _counter(name):
    return get_telemetry().counter(name).value


class _FakeResult:
    status = CheckStatus.SUCCESS
    metrics = {}


# --------------------------------------------------------------------------
# FleetSupervisor units (ManualClock, hand-driven heartbeat/poll)
# --------------------------------------------------------------------------


class TestFleetSupervisor:
    def _sup(self, tmp_path, clk, replica, **kw):
        kw.setdefault("heartbeat_s", 1.0)
        kw.setdefault("lease_timeout_s", 5.0)
        return FleetSupervisor(
            str(tmp_path / "fleet"),
            replica,
            journal_dir=str(tmp_path / f"journal-{replica}"),
            clock=clk,
            **kw,
        )

    def test_register_heartbeat_and_zombie_twin_fencing(self, tmp_path):
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        assert a.epoch == 1
        assert a.heartbeat() is True
        assert a.fenced() is False
        # a twin re-registering under the SAME replica id (restart,
        # duplicate deploy) claims epoch 2 — the original is fenced on
        # its next heartbeat and stays fenced (sticky)
        twin = self._sup(tmp_path, clk, "a")
        assert twin.epoch == 2
        assert a.heartbeat() is False
        assert a.fenced() is True
        assert twin.heartbeat() is True
        assert epoch_fence_check(a) is False
        assert epoch_fence_check(twin) is True
        assert epoch_fence_check(None) is True

    def test_stale_lease_adopted_and_chain_gced(self, tmp_path):
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        seen = []
        b.on_adopt = seen.append
        assert b.poll() == []  # first sight of a's (epoch, stamp)
        clk.advance(4.0)
        a.heartbeat()
        assert b.poll() == []  # stamp moved: staleness clock resets
        clk.advance(5.1)
        adoptions = b.poll()
        assert len(adoptions) == 1
        adoption = adoptions[0]
        assert adoption.replica == "a"
        assert adoption.epoch == 2
        assert adoption.journal_dir == a.journal_dir
        assert adoption.stale_for_s > 5.0
        assert seen == [adoption]
        # the dead replica is fenced the moment it comes back
        assert a.heartbeat() is False
        assert epoch_fence_check(a) is False
        # chain GC: only the adopted top remains for chain a
        storage = b._storage
        a_keys = [
            k for k in storage.list_keys("leases/lease-a-")
            if json.loads(storage.read_bytes(k))["replica"] == "a"
        ]
        assert a_keys == [_lease_key("a", 2)]
        top = json.loads(storage.read_bytes(_lease_key("a", 2)))
        assert top["state"] == "adopted"
        assert top["owner"] == "b"
        # an adopted chain is terminal: later polls skip it
        clk.advance(60.0)
        assert b.poll() == []

    def test_retired_chain_is_never_adopted(self, tmp_path):
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        b.poll()
        a.stop(retire=True)
        clk.advance(60.0)
        assert b.poll() == []

    def test_exactly_one_adopter_wins_the_cas_race(self, tmp_path):
        """Two survivors observe the same expired lease concurrently
        (both read the chain before either claim lands): both compute
        the same next epoch key, the storage CAS admits exactly one."""
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        c = self._sup(tmp_path, clk, "c")
        b.poll(), c.poll()
        clk.advance(5.1)
        c.heartbeat()  # c is alive — only a's lease goes stale
        races_before = _counter("service.fleet.adoption_races_lost")
        assert [ad.replica for ad in b.poll()] == ["a"]
        # c acts on its STALE read of a's epoch-1 lease — the TOCTOU
        # window poll() can't reproduce once the blob says "adopted"
        stale = Lease(
            replica="a", epoch=1, stamp=0, owner="a",
            journal_dir=a.journal_dir,
        )
        assert c._try_adopt(stale, stale_for_s=5.1) is None
        assert c.snapshot()["adoption_races_lost"] == 1
        assert (
            _counter("service.fleet.adoption_races_lost")
            - races_before
            == 1
        )
        assert b.snapshot()["adoptions"][0]["replica"] == "a"
        assert c.snapshot()["adoptions"] == []

    def test_chain_id_prefix_collision_is_harmless(self, tmp_path):
        """Replica ids where one is a prefix of another ("a" and
        "a-b") share a listing prefix; chain ops must trust the blob's
        replica field, not the key."""
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        ab = self._sup(tmp_path, clk, "a-b")
        assert a.heartbeat() is True  # a-b's chain must not fence a
        assert ab.heartbeat() is True
        assert a.epoch == 1 and ab.epoch == 1
        w = self._sup(tmp_path, clk, "w")
        assert set(w.snapshot()["peers"]) == {"a", "a-b"}

    def test_poison_ledger_quarantines_at_distinct_replicas(
        self, tmp_path
    ):
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a", poison_replicas=2)
        b = self._sup(tmp_path, clk, "b", poison_replicas=2)
        key = "dataset:poison-plan"
        assert a.note_crash_loop(key) == 1
        assert a.note_crash_loop(key) == 1  # same replica: no growth
        assert not a.quarantined(key)
        assert b.note_crash_loop(key) == 2
        assert a.quarantined(key) and b.quarantined(key)
        assert a.crashed_replicas(key) == ["a", "b"]
        assert not a.quarantined("dataset:other")

    def test_fenced_supervisor_never_polls_or_adopts(self, tmp_path):
        """A fenced replica must stand down from the WATCH side too:
        a zombie winning an adoption CAS only to drop the replay at
        the service's fence check would strand the orphan's runs
        behind a terminal claim."""
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        b.poll()  # sight a's (epoch, stamp)
        self._sup(tmp_path, clk, "b")  # twin claims b's epoch 2
        assert b.heartbeat() is False
        clk.advance(5.1)
        assert b.poll() == []  # a is stale, but b never watches
        # even a direct claim attempt stands down before the CAS
        stale = Lease(
            replica="a", epoch=1, stamp=0, owner="a",
            journal_dir=a.journal_dir,
        )
        assert b._try_adopt(stale, stale_for_s=5.1) is None
        # a's chain is untouched: still live, no epoch-2 claim
        top = json.loads(b._storage.read_bytes(_lease_key("a", 1)))
        assert top["state"] == "live"
        assert b._storage.read_bytes(_lease_key("a", 2)) is None

    def test_released_claim_leaves_chain_adoptable(self, tmp_path):
        """A replica fenced between the CAS win and the replay hands
        the claim back (release_claim): the chain's previous epoch is
        the top again and a live survivor's normal staleness watch
        adopts it — no runs stranded behind a claim nobody replays."""
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        c = self._sup(tmp_path, clk, "c")
        b.on_adopt = lambda ad: b.release_claim(ad.replica, ad.epoch)
        b.poll(), c.poll()
        clk.advance(5.1)
        c.heartbeat()  # c is alive — only a's lease goes stale to b
        assert b.poll() == []  # won the CAS, then handed the claim back
        assert b.snapshot()["adoptions"] == []
        # the claim blob is gone and the stale live epoch is the top
        # again (release must run BEFORE chain GC or nothing remains)
        assert b._storage.read_bytes(_lease_key("a", 2)) is None
        top = json.loads(b._storage.read_bytes(_lease_key("a", 1)))
        assert top["state"] == "live"
        # c's own staleness clock on a has also expired: c re-claims
        # the SAME epoch (the released key) and the adoption completes
        b.heartbeat()  # b itself is alive — only a is stale to c
        adoptions = c.poll()
        assert [ad.replica for ad in adoptions] == ["a"]
        assert adoptions[0].epoch == 2
        top = json.loads(c._storage.read_bytes(_lease_key("a", 2)))
        assert top["state"] == "adopted" and top["owner"] == "c"

    def test_unfenced_verdict_cached_between_heartbeats(self, tmp_path):
        """fenced() on persist paths must not pay a storage listing
        per call: the unfenced verdict is cached for one heartbeat
        interval on the injected clock; heartbeat() always does a real
        chain read; the sticky fenced flag never reads again."""
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        calls = []
        real = a._storage.list_keys
        a._storage.list_keys = lambda p="": (
            calls.append(p) or real(p)
        )
        assert a.fenced() is False  # cached from registration
        clk.advance(0.5)
        assert a.fenced() is False  # still inside the heartbeat window
        assert calls == []
        clk.advance(0.6)
        assert a.fenced() is False  # window expired: one real re-read
        assert len(calls) == 1
        assert a.fenced() is False  # fresh verdict re-cached
        assert len(calls) == 1
        assert a.heartbeat() is True  # heartbeats always really read
        assert len(calls) == 2
        self._sup(tmp_path, clk, "a")  # twin fences a
        assert a.heartbeat() is False
        reads_when_fenced = len(calls)
        assert a.fenced() is True  # sticky: no further storage reads
        assert len(calls) == reads_when_fenced

    def test_child_epoch_guard_round_trip(self, tmp_path, monkeypatch):
        clk = ManualClock()
        a = self._sup(tmp_path, clk, "a")
        b = self._sup(tmp_path, clk, "b")
        guard_a = a.child_guard()
        monkeypatch.delenv(CHILD_EPOCH_ENV, raising=False)
        assert child_epoch_fenced() is False  # no guard: stay open
        monkeypatch.setenv(CHILD_EPOCH_ENV, guard_a)
        assert child_epoch_fenced() is False  # a still owns epoch 1
        b.poll()
        clk.advance(5.1)
        assert len(b.poll()) == 1  # b adopts a's chain at epoch 2
        assert child_epoch_fenced() is True  # a's child is now fenced
        monkeypatch.setenv(CHILD_EPOCH_ENV, b.child_guard())
        assert child_epoch_fenced() is False  # b's own child stays open
        monkeypatch.setenv(CHILD_EPOCH_ENV, "not json")
        assert child_epoch_fenced() is False  # torn guard: stay open


# --------------------------------------------------------------------------
# In-process two-replica services: adoption + zombie fencing
# --------------------------------------------------------------------------


class TestServiceFleetFencing:
    def _request(self, dataset_key="shared"):
        return RunRequest(
            tenant="acme",
            checks=(),
            dataset_key=dataset_key,
            dataset_factory=lambda: None,
            priority=Priority.STANDARD,
        )

    def test_zombie_replica_drops_all_persists(self, tmp_path):
        """svc_a pauses (never started: a stand-in for a GC pause or
        partition), svc_b adopts its journal. The revived svc_a must
        (1) refuse new admissions with FencedReplica, (2) add ZERO
        bytes to any journal, (3) never reach a repository save."""
        clk = ManualClock()
        fleet_dir = str(tmp_path / "fleet")
        ja, jb = str(tmp_path / "ja"), str(tmp_path / "jb")
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
        ):
            svc_a = VerificationService(
                workers=1, isolated=False, journal_dir=ja,
                fleet_dir=fleet_dir, replica_id="a",
                clock=clk, execute=lambda t: _FakeResult(),
            )
            svc_b = VerificationService(
                workers=1, isolated=False, journal_dir=jb,
                fleet_dir=fleet_dir, replica_id="b",
                clock=clk, execute=lambda t: _FakeResult(),
                adopt_resolve=lambda entry: self._request(
                    entry["dataset_key"]
                ),
            )
        ha = svc_a.submit(self._request("ds-one"))
        svc_a.submit(self._request("ds-two"))
        assert len(RunJournal(ja).pending_runs()) == 2

        adopted_before = _counter("service.fleet.runs_adopted")
        assert svc_b.fleet.poll() == []
        clk.advance(5.1)
        assert len(svc_b.fleet.poll()) == 1
        # both pending runs re-admitted in b, exactly once
        assert len(svc_b.adopted_runs()) == 2
        assert (
            _counter("service.fleet.runs_adopted") - adopted_before == 2
        )
        entries = RunJournal(jb).pending_runs()
        assert sorted(e["adopted_from"] for e in entries.values()) == [
            "run-1", "run-2"
        ]
        assert all(
            e["adopted_replica"] == "a" for e in entries.values()
        )
        # the orphan journal is all-terminal and compacted
        assert RunJournal(ja).pending_runs() == {}

        # (1) zombie admission refused
        fenced_before = _counter("service.fleet.fenced_writes")
        with pytest.raises(FencedReplica):
            svc_a.submit(self._request("ds-three"))
        # (2) zombie journal writes are dropped bit-for-bit: no file
        # in the journal dir grows or appears
        def _ledger(root):
            return sorted(
                (f, os.path.getsize(os.path.join(root, f)))
                for f in os.listdir(root)
                if os.path.isfile(os.path.join(root, f))
            )
        before = _ledger(ja)
        ha._state = RunState.DONE
        svc_a._journal_terminal(ha)
        assert _ledger(ja) == before
        # (3) repository saves are dropped before touching the repo
        class _Repo:
            calls = 0
            def save(self, *a, **kw):
                self.calls += 1
        repo = _Repo()
        service_module._persist_member_result(
            repo, None, None, slo=None, fleet=svc_a.fleet
        )
        service_module._persist_slo_records(
            repo, None, None, fleet=svc_a.fleet
        )
        assert repo.calls == 0
        assert _counter("service.fleet.fenced_writes") > fenced_before
        # every dropped write is visible on the health plane
        assert svc_a.health()["fleet"]["fenced"] is True
        assert svc_b.health()["fleet"]["fenced"] is False

    def test_quarantined_plan_not_readopted(self, tmp_path):
        """A plan key that crash-looped poison_replicas DISTINCT
        replicas is refused at adoption and failed terminally in the
        orphan journal instead of walking the fleet."""
        clk = ManualClock()
        fleet_dir = str(tmp_path / "fleet")
        ja, jb = str(tmp_path / "ja"), str(tmp_path / "jb")
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
            service_fleet_poison_replicas=2,
        ):
            svc_a = VerificationService(
                workers=1, isolated=False, journal_dir=ja,
                fleet_dir=fleet_dir, replica_id="a",
                clock=clk, execute=lambda t: _FakeResult(),
            )
            svc_b = VerificationService(
                workers=1, isolated=False, journal_dir=jb,
                fleet_dir=fleet_dir, replica_id="b",
                clock=clk, execute=lambda t: _FakeResult(),
                adopt_resolve=lambda entry: self._request(
                    entry["dataset_key"]
                ),
            )
        svc_a.submit(self._request("poison"))
        svc_a.fleet.note_crash_loop("dataset:poison")
        svc_b.fleet.note_crash_loop("dataset:poison")
        poisoned_before = _counter("service.fleet.poisoned_runs")
        svc_b.fleet.poll()
        clk.advance(5.1)
        assert len(svc_b.fleet.poll()) == 1
        assert svc_b.adopted_runs() == []
        assert (
            _counter("service.fleet.poisoned_runs") - poisoned_before
            == 1
        )
        assert RunJournal(ja).pending_runs() == {}


# --------------------------------------------------------------------------
# Write-ahead adoption intents: the double-failure recovery road
# --------------------------------------------------------------------------


class TestAdoptionIntentRecovery:
    def _request(self, dataset_key="shared"):
        return RunRequest(
            tenant="acme",
            checks=(),
            dataset_key=dataset_key,
            dataset_factory=lambda: None,
            priority=Priority.STANDARD,
        )

    def _service(self, journal_dir, fleet_dir, replica, clk):
        return VerificationService(
            workers=1, isolated=False, journal_dir=journal_dir,
            fleet_dir=fleet_dir, replica_id=replica,
            clock=clk, execute=lambda t: _FakeResult(),
            adopt_resolve=lambda entry: self._request(
                entry["dataset_key"]
            ),
        )

    def test_adopter_crash_after_claim_finished_by_its_adopter(
        self, tmp_path
    ):
        """THE run-loss window the intent machinery closes: replica b
        wins the claim CAS on dead a's chain but dies before
        journaling any of a's runs. The claim is terminal — nothing
        ever re-polls it — but b's write-ahead adoption intent
        survives in b's journal, so whoever adopts b finishes the
        half-done adoption: a's runs land in c, runs_lost == 0 across
        the DOUBLE failure."""
        clk = ManualClock()
        fleet_dir = str(tmp_path / "fleet")
        ja, jb, jc = (
            str(tmp_path / d) for d in ("ja", "jb", "jc")
        )
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
        ):
            svc_a = self._service(ja, fleet_dir, "a", clk)
            svc_b = self._service(jb, fleet_dir, "b", clk)
            svc_c = self._service(jc, fleet_dir, "c", clk)
        svc_a.submit(self._request("ds-one"))
        svc_a.submit(self._request("ds-two"))
        assert len(RunJournal(ja).pending_runs()) == 2

        # b "crashes" between winning the claim CAS and the replay:
        # the intent has landed durably (on_adopt_intent fires before
        # the CAS), the replay callback never runs
        def _die_mid_adoption(adoption):
            raise RuntimeError("adopter crashed before the replay")

        svc_b.fleet.on_adopt = _die_mid_adoption
        svc_b.fleet.poll(), svc_c.fleet.poll()  # sight the peers
        clk.advance(5.1)
        svc_c.fleet.heartbeat()  # c stays live while b claims a
        with pytest.raises(RuntimeError):
            svc_b.fleet.poll()
        # the crash left: a's chain terminally claimed, zero runs
        # moved, and b's journal holding the unfinished intent
        top = json.loads(
            svc_c.fleet._storage.read_bytes(_lease_key("a", 2))
        )
        assert top["state"] == "adopted" and top["owner"] == "b"
        assert svc_b.adopted_runs() == []
        (intent,) = RunJournal(jb).pending_adoptions()
        assert (intent["replica"], intent["epoch"]) == ("a", 2)
        assert intent["journal_dir"] == ja

        # b now dies for real (stops heartbeating); c adopts b's
        # chain, finds the pending intent, and finishes the adoption
        # by re-claiming a's chain at the NEXT epoch
        finished_before = _counter("service.fleet.adoptions_finished")
        clk.advance(5.1)
        adoptions = svc_c.fleet.poll()
        assert [ad.replica for ad in adoptions] == ["b"]
        assert (
            _counter("service.fleet.adoptions_finished")
            - finished_before
            == 1
        )
        # a's two runs landed in c — exactly once, nothing lost
        assert len(svc_c.adopted_runs()) == 2
        entries = RunJournal(jc).pending_runs()
        assert sorted(
            e["adopted_from"] for e in entries.values()
        ) == ["run-1", "run-2"]
        assert all(
            e["adopted_replica"] == "a" for e in entries.values()
        )
        # the finisher claimed epoch 3 on a's chain (CAS-unique even
        # on a terminal chain)
        top = json.loads(
            svc_c.fleet._storage.read_bytes(_lease_key("a", 3))
        )
        assert top["state"] == "adopted" and top["owner"] == "c"
        # every journal is clean: a all-terminal, b's intent closed by
        # the finisher, c's own intents bracketed and compacted
        assert RunJournal(ja).pending_runs() == {}
        assert RunJournal(jb).pending_adoptions() == []
        assert RunJournal(jc).pending_adoptions() == []
        # the zombie b stays fenced out
        assert svc_b.fleet.heartbeat() is False

    def test_lost_claim_race_closes_the_intent(self, tmp_path):
        """An intent whose claim CAS LOSES must be closed (status
        race_lost) — otherwise every later adopter of this journal
        would replay a race this replica never won."""
        clk = ManualClock()
        fleet_dir = str(tmp_path / "fleet")
        ja, jb, jc = (
            str(tmp_path / d) for d in ("ja", "jb", "jc")
        )
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
        ):
            svc_a = self._service(ja, fleet_dir, "a", clk)
            svc_b = self._service(jb, fleet_dir, "b", clk)
            svc_c = self._service(jc, fleet_dir, "c", clk)
        svc_a.submit(self._request("ds-one"))
        svc_b.fleet.poll(), svc_c.fleet.poll()
        clk.advance(5.1)
        svc_b.fleet.heartbeat(), svc_c.fleet.heartbeat()
        assert len(svc_b.fleet.poll()) == 1  # b wins the adoption
        # c acts on its stale read of a's epoch-1 lease and loses
        stale = Lease(
            replica="a", epoch=1, stamp=0, owner="a", journal_dir=ja,
        )
        assert svc_c.fleet._try_adopt(stale, stale_for_s=5.1) is None
        # c's journal holds the full bracket: intent + race_lost done
        records = [
            (r["type"], r.get("status"))
            for r in RunJournal(jc).replay()
            if r["type"].startswith("adoption_")
        ]
        assert records == [
            ("adoption_intent", None), ("adoption_done", "race_lost"),
        ]
        assert RunJournal(jc).pending_adoptions() == []
        # and a recover() of c replays nothing for it
        assert svc_c.recover() == []

    def test_pending_adoptions_bracket_and_compaction(self, tmp_path):
        """Journal semantics under the intents: an intent with no done
        record stays pending across compaction (it is a crash's only
        road back); a matched intent/done pair is dead weight and
        compacts away; run records are untouched by either."""
        j = RunJournal(str(tmp_path / "j"))
        j.record_submitted("run-1", tenant="acme", dataset_key="ds")
        j.record_adoption_intent("a", "/ja", 2)
        j.record_adoption_intent("x", "/jx", 5)
        j.record_adoption_done("x", 5, status="race_lost")
        pend = j.pending_adoptions()
        assert [(p["replica"], p["epoch"]) for p in pend] == [("a", 2)]
        assert pend[0]["journal_dir"] == "/ja"
        j.compact()
        # the pending intent and the live run both survived; the
        # matched (x, 5) bracket is gone
        pend = j.pending_adoptions()
        assert [(p["replica"], p["epoch"]) for p in pend] == [("a", 2)]
        assert set(j.pending_runs()) == {"run-1"}
        assert not any(
            r.get("replica") == "x" for r in j.replay()
        )
        # closing the intent makes the whole bracket compactable
        j.record_adoption_done("a", 2, status="adopted")
        j.compact()
        assert j.pending_adoptions() == []
        assert not any(
            r["type"].startswith("adoption_") for r in j.replay()
        )
        assert set(j.pending_runs()) == {"run-1"}

    def test_restarted_replica_finishes_its_own_intent(self, tmp_path):
        """The same half-done adoption healed WITHOUT a third replica:
        the crashed adopter restarts, re-registers a fresh epoch, and
        recover() walks its own pending intents."""
        clk = ManualClock()
        fleet_dir = str(tmp_path / "fleet")
        ja, jb = str(tmp_path / "ja"), str(tmp_path / "jb")
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
        ):
            svc_a = self._service(ja, fleet_dir, "a", clk)
            svc_b = self._service(jb, fleet_dir, "b", clk)
        svc_a.submit(self._request("ds-one"))
        svc_b.fleet.on_adopt = lambda ad: (_ for _ in ()).throw(
            RuntimeError("crash before replay")
        )
        svc_b.fleet.poll()
        clk.advance(5.1)
        with pytest.raises(RuntimeError):
            svc_b.fleet.poll()
        assert len(RunJournal(jb).pending_adoptions()) == 1
        # b restarts: same journal dir, fresh supervisor epoch
        with config.configure(
            service_fleet_heartbeat_s=1.0,
            service_fleet_lease_timeout_s=5.0,
        ):
            svc_b2 = self._service(jb, fleet_dir, "b", clk)
        recovered = svc_b2.recover()
        assert len(svc_b2.adopted_runs()) == 1
        del recovered  # a's run arrives via adoption, not recovery
        entries = RunJournal(jb).pending_runs()
        assert sorted(
            e.get("adopted_from") for e in entries.values()
        ) == ["run-1"]
        assert RunJournal(jb).pending_adoptions() == []
        assert RunJournal(ja).pending_runs() == {}


# --------------------------------------------------------------------------
# Chaos differential: SIGKILL a whole replica, survivor adopts+resumes
# --------------------------------------------------------------------------


def _fleet_victim(payload):
    """A whole fleet replica that dies by SIGKILL mid-scan: registers
    its lease, journals one run, and hard-crashes the PROCESS at batch
    7 — after the submitted/started records and two durable checkpoint
    cursors (in the SHARED fleet checkpoint dir) have landed."""
    from deequ_tpu.testing.faults import FaultInjectingDataset

    ds = FaultInjectingDataset(
        Dataset.from_pydict(payload["data"]),
        crash_at_batch=7,
        crash_signum=signal.SIGKILL,
    )
    with config.configure(
        checkpoint_every_batches=3, batch_size=104, device_cache_bytes=0,
        service_fleet_heartbeat_s=0.2, service_fleet_lease_timeout_s=1.0,
    ):
        svc = VerificationService(
            workers=1, isolated=False,
            journal_dir=payload["journal_dir"],
            fleet_dir=payload["fleet_dir"],
            replica_id="victim",
        ).start()
        handle = svc.submit(
            RunRequest(
                tenant="acme",
                checks=_checks(),
                dataset=ds,
                priority=Priority.STANDARD,
            )
        )
        handle.wait(timeout=120)  # the SIGKILL lands first
    return "unreachable"


class TestFleetChaosDifferential:
    def test_sigkilled_replica_adopted_and_resumed_bit_identical(
        self, tmp_path
    ):
        data = _table_data()
        fleet_dir = str(tmp_path / "fleet")
        victim_journal = str(tmp_path / "victim-journal")
        survivor_journal = str(tmp_path / "survivor-journal")

        victim = IsolatedRunner(
            key="fleet-victim", max_relaunches=1, timeout_s=300.0,
            use_breaker=False,
        )
        with pytest.raises(CrashLoopError) as excinfo:
            victim.run(
                _fleet_victim,
                {
                    "data": data,
                    "journal_dir": victim_journal,
                    "fleet_dir": fleet_dir,
                },
            )
        assert excinfo.value.last_signal == "SIGKILL"

        # the victim's durable traces survived the kill: a live lease,
        # a pending started run, a checkpoint cursor in the SHARED dir
        pending = RunJournal(victim_journal).pending_runs()
        assert len(pending) == 1
        (orphan_id, entry), = pending.items()
        assert entry["started"] is True
        assert entry["last_checkpoint"] is not None

        tm = get_telemetry()
        resumes_before = tm.counter("engine.resumes").value
        with config.configure(
            checkpoint_every_batches=3, batch_size=104,
            device_cache_bytes=0,
            service_fleet_heartbeat_s=0.2,
            service_fleet_lease_timeout_s=1.0,
        ):
            oracle = VerificationSuite.do_verification_run(
                Dataset.from_pydict(data), _checks()
            )
            t0 = time.monotonic()
            svc = VerificationService(
                workers=1, isolated=False,
                journal_dir=survivor_journal,
                fleet_dir=fleet_dir,
                replica_id="survivor",
                adopt_resolve=lambda entry: RunRequest(
                    tenant=entry["tenant"],
                    checks=_checks(),
                    dataset=Dataset.from_pydict(data),
                ),
            )
            # hand-driven watch loop: first poll sights the dead lease,
            # the second — one lease timeout later — must adopt
            assert svc.fleet.poll() == []
            time.sleep(1.3)
            adoptions = svc.fleet.poll()
            assert len(adoptions) == 1
            time_to_adoption = time.monotonic() - t0
            assert adoptions[0].replica == "victim"
            adopted = svc.adopted_runs()
            assert len(adopted) == 1  # runs_lost == 0
            svc.start()
            try:
                handle = adopted[0]
                assert handle.wait(timeout=120)
                assert handle.status == RunState.DONE
                result = handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=10)
        # adoption happened within ~one lease timeout + poll cadence,
        # not after some multi-cycle backoff
        assert time_to_adoption < 10.0
        assert adoptions[0].stale_for_s < 10.0
        # resumed from the DEAD replica's durable cursor (shared fleet
        # checkpoint dir), not recomputed from scratch
        assert tm.counter("engine.resumes").value - resumes_before == 1
        assert result.status == CheckStatus.SUCCESS
        assert _result_values(result) == _result_values(oracle)
        # exactly-once: the orphan journal is fully terminal, the
        # adopter's journal reaches terminal too — no run persisted
        # twice, none lost
        assert RunJournal(victim_journal).pending_runs() == {}
        assert RunJournal(survivor_journal).pending_runs() == {}
