"""Resilient scan execution (docs/RESILIENCE.md): batch-level retry,
quarantine + graceful degradation, checkpoint/resume, and the
deterministic fault harness (deequ_tpu/testing/faults.py).

The load-bearing differential: an interrupted-then-resumed scan must
produce BIT-IDENTICAL metrics to an uninterrupted one, on the resident,
streaming and mesh paths alike. All faults are seeded/deterministic and
every retry backoff goes through an injected sleep recorder — no test
here ever sleeps wall-clock time.
"""

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine.resilience import (
    BatchIntegrityError,
    RetryPolicy,
    ScanDegradation,
    ScanKilled,
    TransientScanError,
    is_transient,
    resilient_batches,
    retry_transient,
)
from deequ_tpu.engine.scan import AnalysisEngine, _prefetched
from deequ_tpu.io.state_provider import ScanCheckpointer, ScanCursor
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset
from deequ_tpu.utils.trylike import Failure, Success, Try
from deequ_tpu.verification.suite import VerificationSuite


def _no_sleep(_s: float) -> None:
    pass


FAST_RETRY = RetryPolicy(max_attempts=3, sleep=_no_sleep)


def _table_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.normal(size=n).tolist(),
        "g": (np.arange(n) % 7).tolist(),
    }


ANALYZERS = [
    Size(),
    Completeness("a"),
    Mean("a"),
    ApproxQuantile("a", 0.5),
    Uniqueness(["g"]),
]


def _metric_values(ctx, analyzers=ANALYZERS):
    out = []
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out.append((str(a), value.get()))
    return out


# mode -> (engine factory, config overrides). Mesh batch sizes round up
# to a multiple of the 8 virtual devices, so 104 stays 104 everywhere.
def _mode_setup(mode, cpu_mesh):
    if mode == "resident":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=1 << 30, batch_size=104
        )
    if mode == "streaming":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=0, batch_size=104
        )
    assert mode == "mesh"
    return (lambda **kw: AnalysisEngine(mesh=cpu_mesh, **kw)), dict(
        device_cache_bytes=0, batch_size=104
    )


MODES = ["resident", "streaming", "mesh"]


# --------------------------------------------------------------------------
# RetryPolicy
# --------------------------------------------------------------------------


class TestRetryPolicy:
    def test_delay_is_deterministic_and_jitter_bounded(self):
        policy = RetryPolicy(
            backoff_base_s=0.1, backoff_multiplier=2.0, jitter=0.25
        )
        for batch in range(5):
            for attempt in range(1, 4):
                d1 = policy.delay_s(batch, attempt)
                d2 = policy.delay_s(batch, attempt)
                assert d1 == d2  # pure function, seeded jitter
                base = min(0.1 * 2.0 ** (attempt - 1), policy.backoff_max_s)
                assert base * 0.75 <= d1 <= base * 1.25
        # distinct (batch, attempt) pairs actually get distinct jitter
        delays = {
            policy.delay_s(b, a) for b in range(5) for a in range(1, 4)
        }
        assert len(delays) > 5

    def test_delay_respects_cap(self):
        policy = RetryPolicy(
            backoff_base_s=1.0, backoff_max_s=2.0, jitter=0.0
        )
        assert policy.delay_s(0, 10) == 2.0

    def test_different_seed_different_jitter(self):
        a = RetryPolicy(seed=0).delay_s(3, 1)
        b = RetryPolicy(seed=1).delay_s(3, 1)
        assert a != b

    def test_sleep_is_injectable(self):
        recorded = []
        policy = RetryPolicy(sleep=recorded.append)
        policy.sleep_for(1234.5)  # would block for 20 min if real
        assert recorded == [1234.5]

    def test_transient_taxonomy(self):
        assert is_transient(TransientScanError("x"))
        assert is_transient(OSError("io"))
        assert is_transient(TimeoutError("slow"))
        assert not is_transient(ValueError("decode"))
        assert not is_transient(BatchIntegrityError("short"))


class TestRetryTransient:
    def test_retries_then_succeeds(self):
        sleeps = []
        policy = RetryPolicy(max_attempts=3, sleep=sleeps.append)
        degr = ScanDegradation()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientScanError("hiccup")
            return "ok"

        assert retry_transient(flaky, policy, 7, degr) == "ok"
        assert calls["n"] == 3
        assert degr.retries == 2
        assert sleeps == [policy.delay_s(7, 1), policy.delay_s(7, 2)]

    def test_deterministic_error_never_retried(self):
        degr = ScanDegradation()
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise ValueError("decode error")

        with pytest.raises(ValueError):
            retry_transient(broken, FAST_RETRY, 0, degr)
        assert calls["n"] == 1
        assert degr.retries == 0

    def test_exhaustion_reraises(self):
        degr = ScanDegradation()
        with pytest.raises(TransientScanError):
            retry_transient(
                lambda: (_ for _ in ()).throw(TransientScanError("x")),
                FAST_RETRY,
                0,
                degr,
            )
        assert degr.retries == FAST_RETRY.max_attempts - 1


# --------------------------------------------------------------------------
# Try.recover / Try.of_retry (utils/trylike.py)
# --------------------------------------------------------------------------


class TestTryRecover:
    def test_success_passes_through(self):
        assert Success(5).recover(lambda e: 0) == Success(5)

    def test_failure_recovers(self):
        exc = ValueError("boom")
        out = Failure(exc).recover(lambda e: f"saw {e}")
        assert out == Success("saw boom")

    def test_raising_recovery_is_failure(self):
        def bad(_e):
            raise KeyError("worse")

        out = Failure(ValueError("boom")).recover(bad)
        assert out.is_failure
        assert isinstance(out.exception, KeyError)

    def test_of_retry_succeeds_within_budget(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("not yet")
            return 42

        assert Try.of_retry(flaky, attempts=5) == Success(42)
        assert calls["n"] == 3  # stops at first success

    def test_of_retry_keeps_last_failure(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise RuntimeError(f"attempt {calls['n']}")

        out = Try.of_retry(broken, attempts=3)
        assert calls["n"] == 3
        assert out.is_failure
        assert str(out.exception) == "attempt 3"

    def test_of_retry_zero_attempts_is_failure(self):
        assert Try.of_retry(lambda: 1, attempts=0).is_failure


# --------------------------------------------------------------------------
# _prefetched worker-thread exception propagation
# --------------------------------------------------------------------------


class TestPrefetched:
    def test_yields_then_raises_original_exception(self):
        def source():
            yield 1
            yield 2
            raise TransientScanError("read failed mid-stream")

        got = []
        with pytest.raises(TransientScanError, match="mid-stream") as info:
            for item in _prefetched(source()):
                got.append(item)
        # FIFO: items produced before the failure arrive first — the
        # engine's failing-index arithmetic depends on this
        assert got == [1, 2]
        # the ORIGINAL traceback is attached: the raising frame inside
        # source() is visible, not just the re-raise site
        tb = info.value.__traceback__
        frames = []
        while tb is not None:
            frames.append(tb.tb_frame.f_code.co_name)
            tb = tb.tb_next
        assert "source" in frames

    def test_clean_iteration_unchanged(self):
        assert list(_prefetched(iter(range(10)))) == list(range(10))

    def test_immediate_error_propagates(self):
        def dead():
            raise OSError("no such source")
            yield  # pragma: no cover

        with pytest.raises(OSError, match="no such source"):
            list(_prefetched(dead()))


# --------------------------------------------------------------------------
# resilient_batches driver (unit level)
# --------------------------------------------------------------------------


class TestResilientBatches:
    def _driver(self, make_iter, validate=None, policy=FAST_RETRY):
        degr = ScanDegradation()
        items = list(
            resilient_batches(
                make_iter, policy, degr, rows_for=lambda i: 10,
                validate=validate,
            )
        )
        return items, degr

    def test_transient_restarts_from_failing_index(self):
        ledger = {"fails_left": 2, "starts": []}

        def make_iter(start):
            ledger["starts"].append(start)

            def gen():
                for i in range(start, 6):
                    if i == 3 and ledger["fails_left"] > 0:
                        ledger["fails_left"] -= 1
                        raise TransientScanError("flaky batch 3")
                    yield f"item{i}"

            return gen()

        items, degr = self._driver(make_iter)
        assert [i for i, _ in items] == list(range(6))
        assert [x for _, x in items] == [f"item{i}" for i in range(6)]
        assert ledger["starts"] == [0, 3, 3]  # restarted AT the failure
        assert degr.retries == 2
        assert not degr.is_degraded

    def test_exhaustion_quarantines_and_continues(self):
        def make_iter(start):
            def gen():
                for i in range(start, 5):
                    if i == 2:
                        raise TransientScanError("always fails")
                    yield i

            return gen()

        items, degr = self._driver(make_iter)
        assert [i for i, _ in items] == [0, 1, 3, 4]
        assert degr.batches_quarantined == 1
        assert degr.rows_skipped == 10
        assert degr.failures[0].batch_index == 2
        assert degr.failures[0].attempts == FAST_RETRY.max_attempts

    def test_deterministic_error_quarantines_immediately(self):
        starts = []

        def make_iter(start):
            starts.append(start)

            def gen():
                for i in range(start, 4):
                    if i == 1:
                        raise ValueError("bad decode")
                    yield i

            return gen()

        items, degr = self._driver(make_iter)
        assert [i for i, _ in items] == [0, 2, 3]
        assert degr.batches_quarantined == 1
        assert degr.failures[0].attempts == 1
        assert degr.failures[0].error_class == "ValueError"
        assert starts == [0, 2]  # no retry restart for deterministic

    def test_validate_quarantines_without_restart(self):
        starts = []

        def make_iter(start):
            starts.append(start)
            return iter(range(start, 5))

        def validate(item):
            if item == 3:
                raise BatchIntegrityError("short batch")

        items, degr = self._driver(make_iter, validate=validate)
        assert [x for _, x in items] == [0, 1, 2, 4]
        assert degr.batches_quarantined == 1
        assert starts == [0]  # the source was never restarted

    def test_scan_killed_passes_through(self):
        def make_iter(start):
            def gen():
                yield 0
                raise ScanKilled("process death")

            return gen()

        degr = ScanDegradation()
        with pytest.raises(ScanKilled):
            list(
                resilient_batches(
                    make_iter, FAST_RETRY, degr, rows_for=lambda i: 1
                )
            )
        assert not degr.is_degraded  # a kill is not a quarantine


class TestScanDegradationRecord:
    def test_merge(self):
        a = ScanDegradation()
        a.record_quarantine(1, 100, ValueError("x"), 1)
        b = ScanDegradation()
        b.record_quarantine(5, 50, OSError("y"), 3)
        b.record_retry()
        merged = a.merge(b)
        assert merged.batches_quarantined == 2
        assert merged.rows_skipped == 150
        assert merged.retries == 1
        assert merged.error_classes == ["OSError", "ValueError"]
        assert ScanDegradation.merge_optional(None, a) is a
        assert ScanDegradation.merge_optional(a, None) is a

    def test_to_dict_round_trips_failures(self):
        d = ScanDegradation()
        d.record_quarantine(2, 10, ValueError("boom"), 2)
        rec = d.to_dict()
        assert rec["failures"][0]["batch_index"] == 2
        assert rec["failures"][0]["message"] == "boom"


# --------------------------------------------------------------------------
# Engine-level: retry / quarantine / checkpoint / resume, all modes
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", MODES)
class TestEngineResilience:
    def test_transient_faults_bit_identical(self, mode, cpu_mesh):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        data = _table_data()
        with config.configure(scan_retry=FAST_RETRY, **opts):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
            tm = get_telemetry()
            before = tm.counter("engine.batch_retries").value
            ds = FaultInjectingDataset(
                Dataset.from_pydict(data), transient={2: 1, 5: 2}
            )
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        assert _metric_values(ctx) == ref
        assert tm.counter("engine.batch_retries").value - before == 3
        assert ctx.degradation is not None and ctx.degradation.retries == 3
        assert not ctx.degradation.is_degraded

    def test_permanent_fault_quarantines_and_completes(self, mode, cpu_mesh):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        tm = get_telemetry()
        before = tm.counter("engine.batches_quarantined").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), permanent={3}
        )
        with config.configure(scan_retry=FAST_RETRY, **opts):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        degr = ctx.degradation
        assert degr is not None and degr.is_degraded
        assert degr.batches_quarantined == 1
        assert degr.rows_skipped == 104  # one full interior batch
        assert degr.error_classes == ["ValueError"]
        assert tm.counter("engine.batches_quarantined").value - before == 1
        # the scan COMPLETED: every metric computed, over partial data
        size = ctx.metric(Size()).value.get()
        assert size == 1000 - 104

    def test_retry_exhaustion_quarantines(self, mode, cpu_mesh):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), transient={4: 99}
        )
        with config.configure(
            scan_retry=RetryPolicy(max_attempts=2, sleep=_no_sleep), **opts
        ):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        degr = ctx.degradation
        assert degr.batches_quarantined == 1
        assert degr.failures[0].error_class == "TransientScanError"
        assert degr.failures[0].attempts == 2

    def test_kill_then_resume_bit_identical(self, mode, cpu_mesh, tmp_path):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        data = _table_data()
        tm = get_telemetry()
        with config.configure(
            scan_retry=FAST_RETRY, checkpoint_every_batches=3, **opts
        ):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
            ckpt = ScanCheckpointer(str(tmp_path))
            engine = make_engine(checkpointer=ckpt)
            ds = FaultInjectingDataset(
                Dataset.from_pydict(data), kill_at_batch=7
            )
            ckpts_before = tm.counter("engine.checkpoints_written").value
            resumes_before = tm.counter("engine.resumes").value
            with pytest.raises(ScanKilled):
                AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
            assert tm.counter("engine.checkpoints_written").value > ckpts_before
            # a checkpoint survived the kill
            assert ckpt._storage.list_keys("scan-ckpt-")
            ctx = AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
            assert tm.counter("engine.resumes").value - resumes_before == 1
        assert _metric_values(ctx) == ref
        # completion cleared the checkpoint — nothing stale to resume into
        assert ckpt._storage.list_keys("scan-ckpt-") == []

    def test_source_fingerprint_invalidates_checkpoint(
        self, mode, cpu_mesh, tmp_path
    ):
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        tm = get_telemetry()
        with config.configure(
            scan_retry=FAST_RETRY, checkpoint_every_batches=3, **opts
        ):
            ckpt = ScanCheckpointer(str(tmp_path))
            engine = make_engine(checkpointer=ckpt)
            ds = FaultInjectingDataset(
                Dataset.from_pydict(_table_data(seed=0)), kill_at_batch=7
            )
            with pytest.raises(ScanKilled):
                AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
            assert ckpt._storage.list_keys("scan-ckpt-")
            # a DIFFERENT source must not resume from that checkpoint
            other = Dataset.from_pydict(_table_data(seed=1))
            resumes_before = tm.counter("engine.resumes").value
            ctx = AnalysisRunner.do_analysis_run(
                other, ANALYZERS, engine=make_engine(checkpointer=ckpt)
            )
            assert tm.counter("engine.resumes").value == resumes_before
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(_table_data(seed=1)), ANALYZERS,
                    engine=make_engine(),
                )
            )
        assert _metric_values(ctx) == ref


class TestCorruptBatches:
    @pytest.mark.parametrize("mode", ["streaming", "mesh"])
    def test_corrupt_batch_quarantined(self, mode, cpu_mesh):
        """Both wire formats: the packed path detects corruption inside
        pack_host_batch, the mesh (non-packed) path via the validate
        callback — either way the batch is quarantined, not shipped."""
        make_engine, opts = _mode_setup(mode, cpu_mesh)
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), corrupt={1}
        )
        with config.configure(scan_retry=FAST_RETRY, **opts):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        degr = ctx.degradation
        assert degr.batches_quarantined == 1
        assert degr.error_classes == ["BatchIntegrityError"]
        assert ctx.metric(Size()).value.get() == 1000 - 104


class TestMeshSpillResume:
    """Satellite: kill-at-batch-N then resume stays bit-identical on the
    mesh path for scalar + dense-grouping + one-pass-spill plans — the
    checkpoint carries collector key buffers (device-result states) and
    the structural plan token covers plans the jit cache cannot."""

    @pytest.fixture
    def spill_data(self):
        rng = np.random.default_rng(42)
        n = 4000
        return {
            "v": rng.normal(size=n).tolist(),
            "dense_g": (np.arange(n) % 5).tolist(),
            "id": rng.integers(0, 2**40, n).tolist(),  # spill plan
        }

    @pytest.mark.parametrize("one_pass", [True, False])
    def test_mixed_suite_resume(
        self, cpu_mesh, tmp_path, spill_data, one_pass
    ):
        analyzers = [
            Size(),
            Mean("v"),
            Uniqueness(["dense_g"]),  # dense grouping
            Uniqueness(["id"]),  # high-cardinality spill
        ]
        overrides = dict(
            device_cache_bytes=0,
            batch_size=512,
            scan_retry=FAST_RETRY,
            checkpoint_every_batches=2,
            one_pass_spill=one_pass,
            dense_grouping_budget_bytes=4 * 1024,  # force the spill path
        )
        with config.configure(**overrides):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(spill_data), analyzers,
                    engine=AnalysisEngine(mesh=cpu_mesh),
                ),
                analyzers,
            )
            engine = AnalysisEngine(
                mesh=cpu_mesh, checkpointer=ScanCheckpointer(str(tmp_path))
            )
            ds = FaultInjectingDataset(
                Dataset.from_pydict(spill_data), kill_at_batch=5
            )
            with pytest.raises(ScanKilled):
                AnalysisRunner.do_analysis_run(ds, analyzers, engine=engine)
            ctx = AnalysisRunner.do_analysis_run(
                ds, analyzers, engine=engine
            )
        assert _metric_values(ctx, analyzers) == ref


# --------------------------------------------------------------------------
# Degradation -> verification status (config.degradation_policy)
# --------------------------------------------------------------------------


class TestDegradationPolicy:
    def _degraded_result(self, policy):
        # checks that PASS on the partial data — status movement below
        # comes from the degradation floor alone
        check = (
            Check(CheckLevel.ERROR, "robust checks")
            .has_completeness("a", lambda v: v == 1.0)
            .has_size(lambda s: s > 0)
        )
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), permanent={2}
        )
        with config.configure(
            device_cache_bytes=0,
            batch_size=104,
            scan_retry=FAST_RETRY,
            degradation_policy=policy,
        ):
            return VerificationSuite.do_verification_run(ds, [check])

    def test_fail_policy_floors_to_error(self):
        result = self._degraded_result("fail")
        assert result.status == CheckStatus.ERROR
        assert result.degradation.batches_quarantined == 1

    def test_warn_policy_floors_to_warning(self):
        result = self._degraded_result("warn")
        assert result.status == CheckStatus.WARNING
        assert result.degradation.is_degraded

    def test_tolerate_policy_keeps_check_status(self):
        result = self._degraded_result("tolerate")
        assert result.status == CheckStatus.SUCCESS
        # the record still rides the result for consumers to inspect
        assert result.degradation.rows_skipped == 104

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError, match="degradation_policy"):
            self._degraded_result("yolo")

    def test_clean_run_has_no_degradation(self):
        check = Check(CheckLevel.ERROR, "ok").has_size(lambda s: s == 1000)
        with config.configure(device_cache_bytes=0, batch_size=104):
            result = VerificationSuite.do_verification_run(
                Dataset.from_pydict(_table_data()), [check]
            )
        assert result.status == CheckStatus.SUCCESS
        assert result.degradation is None


# --------------------------------------------------------------------------
# ScanCheckpointer + storage (io layer)
# --------------------------------------------------------------------------


class TestScanCheckpointer:
    def _cursor(self, fp="parquet-abc", batch_size=64):
        return ScanCursor(
            batch_index=6, row_offset=384,
            source_fingerprint=fp, batch_size=batch_size,
        )

    def test_save_load_roundtrip(self, tmp_path):
        ckpt = ScanCheckpointer(str(tmp_path))
        states = ({"count": np.int64(7)}, np.arange(4))
        degr = ScanDegradation()
        degr.record_quarantine(1, 64, ValueError("x"), 1)
        ckpt.save(self._cursor(), "tok1", states, {0: [1.0, 2.0]}, degr)
        payload = ckpt.load("parquet-abc", "tok1")
        assert payload["cursor"].batch_index == 6
        assert payload["host_accs"] == {0: [1.0, 2.0]}
        assert payload["degradation"].batches_quarantined == 1
        np.testing.assert_array_equal(payload["states"][1], np.arange(4))

    def test_wrong_fingerprint_or_token_is_none(self, tmp_path):
        ckpt = ScanCheckpointer(str(tmp_path))
        ckpt.save(self._cursor(), "tok1", (), {}, None)
        assert ckpt.load("parquet-OTHER", "tok1") is None
        assert ckpt.load("parquet-abc", "tok2") is None

    def test_corrupt_blob_is_none(self, tmp_path):
        ckpt = ScanCheckpointer(str(tmp_path))
        ckpt.save(self._cursor(), "tok1", (), {}, None)
        key = ckpt._key("tok1")
        blob = ckpt._storage.read_bytes(key)
        ckpt._storage.write_bytes(key, blob[: len(blob) // 2])  # partial
        assert ckpt.load("parquet-abc", "tok1") is None
        ckpt._storage.write_bytes(key, b"not a pickle at all")
        assert ckpt.load("parquet-abc", "tok1") is None

    def test_clear(self, tmp_path):
        ckpt = ScanCheckpointer(str(tmp_path))
        ckpt.save(self._cursor(), "tok1", (), {}, None)
        ckpt.save(self._cursor(), "tok2", (), {}, None)
        ckpt.clear("tok1")
        assert ckpt.load("parquet-abc", "tok1") is None
        assert ckpt.load("parquet-abc", "tok2") is not None
        ckpt.clear()
        assert ckpt._storage.list_keys("scan-ckpt-") == []

    def test_interval_falls_back_to_config(self, tmp_path):
        assert ScanCheckpointer(str(tmp_path), every_batches=5).interval() == 5
        with config.configure(checkpoint_every_batches=17):
            assert ScanCheckpointer(str(tmp_path)).interval() == 17

    def test_mem_uri_backend(self):
        ckpt = ScanCheckpointer("mem://ckpt-test")
        ckpt.save(self._cursor(), "tok1", (), {}, None)
        assert ckpt.load("parquet-abc", "tok1") is not None
        ckpt.clear()


class TestSourceFingerprints:
    def test_in_memory_fingerprint_tracks_content(self):
        a = Dataset.from_pydict({"x": [1.0, 2.0, 3.0]})
        b = Dataset.from_pydict({"x": [1.0, 2.0, 3.0]})
        c = Dataset.from_pydict({"x": [1.0, 2.0, 4.0]})
        assert a.fingerprint() == b.fingerprint()
        assert a.fingerprint() != c.fingerprint()
        assert a.fingerprint().startswith("mem-")

    def test_parquet_fingerprint_tracks_files(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.data.parquet import ParquetDataset

        path = str(tmp_path / "part.parquet")
        pq.write_table(pa.table({"x": [1.0, 2.0, 3.0]}), path)
        fp1 = ParquetDataset(path).fingerprint()
        assert fp1.startswith("parquet-")
        assert ParquetDataset(path).fingerprint() == fp1
        pq.write_table(pa.table({"x": [9.0, 9.0, 9.0, 9.0]}), path)
        assert ParquetDataset(path).fingerprint() != fp1


# --------------------------------------------------------------------------
# Repository crash-safety (satellite)
# --------------------------------------------------------------------------


class TestRepositoryCorruption:
    def test_corrupt_file_reads_as_empty_and_recovers(self, tmp_path):
        from deequ_tpu.repository.base import AnalysisResult, ResultKey
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        from deequ_tpu.analyzers.runner import AnalyzerContext

        path = tmp_path / "metrics.json"
        repo = FileSystemMetricsRepository(str(path))
        key = ResultKey.of(1000, {"env": "test"})
        ctx = AnalysisRunner.do_analysis_run(
            Dataset.from_pydict({"x": [1.0, 2.0]}), [Size()]
        )
        repo.save(AnalysisResult(key, ctx))
        assert repo.load_by_key(key) is not None

        # a kill mid-write on a non-atomic backend leaves half a file
        good = path.read_bytes()
        path.write_bytes(good[: len(good) // 2])
        tm = get_telemetry()
        before = tm.counter("repository.corrupt_files").value
        assert repo.load_by_key(key) is None  # tolerated, not raised
        assert tm.counter("repository.corrupt_files").value == before + 1

        # and the next save fully recovers the repository
        repo.save(AnalysisResult(key, ctx))
        assert repo.load_by_key(key) is not None

    def test_garbage_bytes_tolerated(self, tmp_path):
        from deequ_tpu.repository.fs import FileSystemMetricsRepository

        path = tmp_path / "metrics.json"
        path.write_bytes(b"\x00\xff garbage \x80")
        repo = FileSystemMetricsRepository(str(path))
        assert repo.load().get() == []


# --------------------------------------------------------------------------
# Telemetry surface: counters exist and obs_report renders them
# --------------------------------------------------------------------------


class TestResilienceTelemetry:
    def test_obs_report_renders_resilience_section(self, tmp_path):
        from tools.obs_report import render_run

        tm = get_telemetry()
        with config.configure(
            device_cache_bytes=0,
            batch_size=104,
            scan_retry=FAST_RETRY,
            checkpoint_every_batches=3,
        ):
            with tm.run("resilience-report") as cap:
                ds = FaultInjectingDataset(
                    Dataset.from_pydict(_table_data()),
                    transient={1: 1},
                    permanent={4},
                )
                engine = AnalysisEngine(
                    checkpointer=ScanCheckpointer(str(tmp_path))
                )
                AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
        summary = cap.final
        text = render_run(summary)
        assert "resilience" in text
        assert "engine.batch_retries" in text
        assert "engine.batches_quarantined" in text
        assert "engine.checkpoints_written" in text
        assert "quarantined batch 4" in text
