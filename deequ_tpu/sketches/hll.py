"""HyperLogLog primitives for the device pass.

Reference: ``analyzers/catalyst/StatefulHyperloglogPlus`` (SURVEY.md
§2.3): HLL++ registers as packed words updated per row inside Tungsten;
merge = word-wise max. TPU design (per SURVEY's table): registers are an
int32[m] device vector; the per-batch update is hash -> leading-zero
count -> scatter-max; the merge is an elementwise max (a ``lax.max``
all-reduce across the mesh / across persisted states).

Hashing is built from 32-bit lanes ONLY — the TPU has no native 64-bit
integer path (XLA's x64 rewriter refuses u64 bitcasts), and 32-bit
murmur-style mixing maps perfectly onto the VPU:

- numerics canonicalize to a (float32, float32 residual) pair — ~48 bits
  of value information, identical for int and float columns of equal
  value (required by incremental merges across datasets);
- the pair's bit patterns mix through murmur3's 32-bit finalizer into
  two independent 32-bit hashes: h1 supplies the register index (top
  P bits), h2 supplies the leading-zero rank;
- strings hash host-side ONCE per dictionary entry (blake2b-8, split
  into two u32 words) into device lookup tables gathered by code.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

P = 14  # precision: m = 2^14 registers => ~0.8% relative error
M = 1 << P

_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_GOLDEN = np.uint32(0x9E3779B9)


def fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finalizer (avalanche); h: uint32 array."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def hash_pair_numeric(
    values: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Canonicalize numerics and produce two independent u32 hashes.

    Canonical form: hi = float32(x), lo = float32(x - hi) — exact for
    integers up to ~2^48 and collision-free for typical float data, and
    IDENTICAL whether the column arrived as int32/int64/float32/float64.
    """
    as_f64 = values.astype(jnp.float64) + 0.0  # -0.0 -> +0.0
    hi = as_f64.astype(jnp.float32)
    lo = (as_f64 - hi.astype(jnp.float64)).astype(jnp.float32) + 0.0
    hi_bits = jax.lax.bitcast_convert_type(hi, jnp.uint32)
    lo_bits = jax.lax.bitcast_convert_type(lo, jnp.uint32)
    h1 = fmix32(lo_bits ^ fmix32(hi_bits ^ _GOLDEN))
    h2 = fmix32(hi_bits ^ fmix32(lo_bits ^ _C1))
    return h1, h2


def dictionary_hash_pairs(
    dictionary: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stable (u32, u32) hash per dictionary entry (host-side, once)."""
    n = max(len(dictionary), 1)
    h1 = np.zeros(n, dtype=np.uint32)
    h2 = np.zeros(n, dtype=np.uint32)
    for i, value in enumerate(dictionary):
        if value is None:
            continue
        digest = hashlib.blake2b(
            str(value).encode("utf-8"), digest_size=8
        ).digest()
        words = np.frombuffer(digest, dtype=np.uint32)
        h1[i], h2[i] = words[0], words[1]
    return h1, h2


def registers_from_hash_pair(
    h1: jnp.ndarray, h2: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """One batch of hash pairs -> int32[M] register vector (scatter-max).

    rho comes from h2's leading zeros (1..33) — supporting max register
    rank 33, ample for cardinalities far beyond 2^40."""
    idx = (h1 >> np.uint32(32 - P)).astype(jnp.int32)
    rho = jnp.minimum(jax.lax.clz(h2) + 1, 33).astype(jnp.int32)
    rho = jnp.where(mask, rho, 0)
    idx = jnp.where(mask, idx, 0)
    return jnp.zeros(M, dtype=jnp.int32).at[idx].max(rho)


def estimate(registers: np.ndarray) -> float:
    """Standard HLL estimator with linear counting for the small range."""
    registers = np.asarray(registers, dtype=np.float64)
    m = float(M)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    raw = alpha * m * m / np.sum(np.exp2(-registers))
    zeros = float(np.count_nonzero(registers == 0))
    if raw <= 2.5 * m and zeros > 0:
        return float(m * np.log(m / zeros))
    return float(raw)
