"""Table-backed metrics repository over a parquet dataset directory.

Reference: ``repository/sparkTable/SparkTableMetricsRepository.scala``
(SURVEY.md §2.5 ⚠ row) — the reference appends each AnalysisResult as a
row of a Spark SQL table (result_key serialized alongside a JSON metric
payload) so repositories can live in a warehouse, be appended
concurrently, and be queried with predicate pushdown.

The TPU-stack-native equivalent of "a Spark table" is an Arrow/parquet
dataset directory: each ``save`` appends ONE small parquet file of one
row (append = new file, the same contract as a warehouse table append —
no read-modify-write, so concurrent writers from different hosts never
conflict). ``load_by_key`` pushes a result_key equality filter into the
Arrow dataset scan; ``load()`` deserializes everything and filters via
the loader API in memory (dataset_date/tags are real columns, so
external warehouse tools can predicate on them directly).

Row schema (mirrors the reference's table layout):
  result_key   : string (canonical JSON of timestamp + tags)
  dataset_date : int64  (the ResultKey timestamp — filterable column)
  tags         : string (JSON object)
  seq          : int64  (monotonic write sequence — last write per key wins)
  serialized_context : string (full AnalysisResult via repository.serde)
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import List, Optional

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)

_SCHEMA = pa.schema(
    [
        ("result_key", pa.string()),
        ("dataset_date", pa.int64()),
        ("tags", pa.string()),
        ("seq", pa.int64()),
        ("serialized_context", pa.string()),
    ]
)


def _key_json(key: ResultKey) -> str:
    return json.dumps(
        {"dataset_date": key.dataset_date, "tags": key.tags_dict},
        sort_keys=True,
    )


class TableMetricsRepository(MetricsRepository):
    """Append-only parquet-table repository (one file per save).

    Last-write-wins ordering uses wall-clock nanoseconds made strictly
    monotonic WITHIN this writer (ties and NTP steps backwards bump
    past the previous seq). Across hosts, ordering is wall-clock
    best-effort — the same contract as any timestamp-ordered warehouse
    append; writers needing strict cross-host ordering must serialize
    saves themselves."""

    def __init__(self, path: str):
        self._path = path
        self._last_seq = 0
        os.makedirs(path, exist_ok=True)
        # sweep stale temp files from crashed writers (reads already
        # ignore them; this bounds disk growth). One hour is far past
        # any live write->rename window, so racing writers are safe.
        cutoff = time.time() - 3600
        for f in os.listdir(path):
            if f.startswith(".") and f.endswith(".tmp"):
                full = os.path.join(path, f)
                try:
                    if os.path.getmtime(full) < cutoff:
                        os.remove(full)
                except OSError:
                    pass  # another sweeper won the race

    def _next_seq(self) -> int:
        self._last_seq = max(time.time_ns(), self._last_seq + 1)
        return self._last_seq

    def save(self, result: AnalysisResult) -> None:
        key = result.result_key
        table = pa.table(
            {
                "result_key": [_key_json(key)],
                "dataset_date": [int(key.dataset_date)],
                "tags": [json.dumps(key.tags_dict, sort_keys=True)],
                "seq": [self._next_seq()],
                "serialized_context": [serde.serialize([result])],
            },
            schema=_SCHEMA,
        )
        # unique filename: appends never clobber (multi-writer safe);
        # write to a dotted temp name and rename into place so a
        # concurrent reader's scan never opens a half-written file —
        # rename is atomic on POSIX, and _scan only selects *.parquet
        # (the temp name has no such suffix) (ADVICE r3 medium)
        name = f"{key.dataset_date}-{uuid.uuid4().hex}.parquet"
        final_path = os.path.join(self._path, name)
        tmp_path = os.path.join(self._path, f".{name}.tmp")
        pq.write_table(table, tmp_path)
        os.rename(tmp_path, final_path)

    def _scan(self, filter_expr=None) -> List[AnalysisResult]:
        # explicit *.parquet selection: in-flight .tmp files and any
        # stray non-parquet file in the directory must not break loads
        files = sorted(
            os.path.join(self._path, f)
            for f in os.listdir(self._path)
            if f.endswith(".parquet")
        )
        if not files:
            return []
        dataset = pads.dataset(files, format="parquet")
        table = dataset.to_table(
            columns=["result_key", "seq", "serialized_context"],
            filter=filter_expr,
        )
        out: List[AnalysisResult] = []
        seen: dict = {}
        for key_json, seq, payload in zip(
            table.column("result_key").to_pylist(),
            table.column("seq").to_pylist(),
            table.column("serialized_context").to_pylist(),
        ):
            # last write per key wins (the reference overwrites on
            # save; an append-only table keeps history — dedupe at read
            # by the write sequence, NOT file enumeration order, which
            # is uuid-random)
            prior = seen.get(key_json)
            if prior is None or seq > prior[0]:
                seen[key_json] = (seq, payload)
        for _, payload in seen.values():
            out.extend(serde.deserialize(payload))
        # deterministic order regardless of file enumeration order:
        # date, then the canonical key json as the same-date tie-break
        out.sort(
            key=lambda r: (r.result_key.dataset_date, _key_json(r.result_key))
        )
        return out

    def load_by_key(self, key: ResultKey) -> Optional[AnalysisResult]:
        import pyarrow.compute as pc

        wanted = _key_json(key)
        for result in self._scan(pc.field("result_key") == wanted):
            if result.result_key == key:
                return result
        return None

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        return MetricsRepositoryMultipleResultsLoader(self._scan())
