from deequ_tpu.profiles.profiler import (
    ColumnProfiler,
    ColumnProfiles,
    NumericColumnProfile,
    StandardColumnProfile,
)
from deequ_tpu.profiles.runner import (
    ColumnProfilerRunBuilder,
    ColumnProfilerRunner,
)

__all__ = [
    "ColumnProfiler",
    "ColumnProfilerRunBuilder",
    "ColumnProfilerRunner",
    "ColumnProfiles",
    "NumericColumnProfile",
    "StandardColumnProfile",
]
