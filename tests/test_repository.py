"""Metrics repository tests: serde goldens for every metric type,
filesystem round-trips, and time-travel queries (reference test model:
AnalysisResultSerdeTest + repository tests — SURVEY.md §4)."""

import json
import os

import numpy as np
import pytest

from deequ_tpu import Dataset
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxQuantiles,
    Completeness,
    DataType,
    Histogram,
    KLLSketch,
    Mean,
    Size,
)
from deequ_tpu.analyzers.runner import AnalyzerContext
from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    InMemoryMetricsRepository,
    ResultKey,
)
from deequ_tpu.repository.fs import FileSystemMetricsRepository


@pytest.fixture(scope="module")
def context():
    """One context containing every metric shape: DoubleMetric,
    KeyedDoubleMetric, HistogramMetric, KLLMetric, and a failure."""
    ds = Dataset.from_pydict(
        {
            "x": [1.0, 2.0, 3.0, 4.0, None],
            "cat": ["a", "b", "a", "a", "b"],
            "s": ["1", "2", "3", "x", None],
        }
    )
    analyzers = [
        Size(),
        Mean("x"),
        Completeness("x"),
        Histogram("cat"),
        ApproxQuantiles("x", (0.25, 0.5, 0.75)),
        KLLSketch("x"),
        DataType("s"),
        Mean("missing_column"),  # -> failure metric
    ]
    return AnalysisRunner.do_analysis_run(ds, analyzers)


class TestSerde:
    def test_round_trip_preserves_everything(self, context):
        key = ResultKey.of(1700000000000, {"pipeline": "daily", "env": "test"})
        text = serde.serialize([AnalysisResult(key, context)])
        results = serde.deserialize(text)
        assert len(results) == 1
        restored = results[0]
        assert restored.result_key == key
        original = context.metric_map
        loaded = restored.analyzer_context.metric_map
        assert set(loaded.keys()) == set(original.keys())
        for analyzer, metric in original.items():
            got = loaded[analyzer]
            assert type(got) is type(metric)
            assert got.name == metric.name
            assert got.instance == metric.instance
            if metric.value.is_failure:
                assert got.value.is_failure
                continue
            want, have = metric.value.get(), got.value.get()
            if isinstance(want, dict):  # KeyedDoubleMetric
                assert have == pytest.approx(want)
            elif hasattr(want, "values"):  # Distribution
                assert {
                    k: (v.absolute, v.ratio) for k, v in have.values.items()
                } == {
                    k: (v.absolute, v.ratio) for k, v in want.values.items()
                }
            elif hasattr(want, "buckets"):  # BucketDistribution
                assert [
                    (b.low_value, b.high_value, b.count) for b in have.buckets
                ] == [
                    (b.low_value, b.high_value, b.count) for b in want.buckets
                ]
            else:
                assert have == pytest.approx(want)

    def test_serialized_form_is_json(self, context):
        key = ResultKey.of(123, {})
        parsed = json.loads(serde.serialize([AnalysisResult(key, context)]))
        assert isinstance(parsed, list)

    def test_failure_metric_round_trip(self, context):
        bad = Mean("missing_column")
        key = ResultKey.of(5, {})
        restored = serde.deserialize(
            serde.serialize([AnalysisResult(key, context)])
        )[0]
        metric = restored.analyzer_context.metric(bad)
        assert metric is not None and metric.value.is_failure


class TestInMemoryRepository:
    def test_save_load_by_key(self, context):
        repo = InMemoryMetricsRepository()
        key = ResultKey.of(100, {"tag": "a"})
        repo.save(AnalysisResult(key, context))
        assert repo.load_by_key(key) is not None
        assert repo.load_by_key(ResultKey.of(100, {"tag": "b"})) is None

    def test_time_travel_and_tags(self, context):
        repo = InMemoryMetricsRepository()
        for t, env in [(100, "dev"), (200, "prod"), (300, "prod")]:
            repo.save(AnalysisResult(ResultKey.of(t, {"env": env}), context))
        assert len(repo.load().after(150).get()) == 2
        assert len(repo.load().before(250).get()) == 2
        assert len(repo.load().after(150).before(250).get()) == 1
        assert len(repo.load().with_tag_values({"env": "prod"}).get()) == 2
        records = (
            repo.load()
            .with_tag_values({"env": "prod"})
            .for_analyzers([Size()])
            .get_success_metrics_as_records()
        )
        assert all(r["name"] == "Size" for r in records)
        assert {r["dataset_date"] for r in records} == {200, 300}
        assert all(r["env"] == "prod" for r in records)


class TestFileSystemRepository:
    def test_directory_path_rejected(self, tmp_path):
        # a trailing separator leaves an empty blob name; must fail
        # fast like the URI branch (r4 advisory)
        with pytest.raises(ValueError):
            FileSystemMetricsRepository(str(tmp_path) + os.sep)

    def test_round_trip(self, context, tmp_path):
        path = os.path.join(tmp_path, "metrics.json")
        repo = FileSystemMetricsRepository(path)
        key = ResultKey.of(100, {"run": "r1"})
        repo.save(AnalysisResult(key, context))
        # a second process/repo instance sees the data
        repo2 = FileSystemMetricsRepository(path)
        loaded = repo2.load_by_key(key)
        assert loaded is not None
        assert loaded.analyzer_context.metric(Size()).value.get() == 5.0

    def test_save_same_key_overwrites(self, context, tmp_path):
        path = os.path.join(tmp_path, "metrics.json")
        repo = FileSystemMetricsRepository(path)
        key = ResultKey.of(100, {})
        repo.save(AnalysisResult(key, context))
        repo.save(AnalysisResult(key, context))
        assert len(repo.load().get()) == 1

    def test_query_across_saves(self, context, tmp_path):
        path = os.path.join(tmp_path, "metrics.json")
        repo = FileSystemMetricsRepository(path)
        for t in (10, 20, 30):
            repo.save(AnalysisResult(ResultKey.of(t, {}), context))
        got = repo.load().after(15).get()
        assert [r.result_key.dataset_date for r in got] == [20, 30]


class TestRunnerRepositoryIntegration:
    def test_reuse_existing_results(self, context):
        """The runner reuses repository metrics instead of recomputing
        (SURVEY.md §2.4 step 1)."""
        repo = InMemoryMetricsRepository()
        key = ResultKey.of(1, {})
        ds = Dataset.from_pydict({"x": [1.0, 2.0, 3.0]})
        ctx1 = (
            AnalysisRunner.on_data(ds)
            .add_analyzer(Mean("x"))
            .use_repository(repo)
            .save_or_append_result(key)
            .run()
        )
        assert ctx1.metric(Mean("x")).value.get() == 2.0
        # different data, same key: reused metric wins (no recompute)
        ds2 = Dataset.from_pydict({"x": [100.0, 200.0]})
        ctx2 = (
            AnalysisRunner.on_data(ds2)
            .add_analyzer(Mean("x"))
            .use_repository(repo)
            .reuse_existing_results_for_key(key)
            .run()
        )
        assert ctx2.metric(Mean("x")).value.get() == 2.0

    def test_fail_if_results_missing(self):
        repo = InMemoryMetricsRepository()
        ds = Dataset.from_pydict({"x": [1.0]})
        with pytest.raises(RuntimeError):
            (
                AnalysisRunner.on_data(ds)
                .add_analyzer(Mean("x"))
                .use_repository(repo)
                .reuse_existing_results_for_key(
                    ResultKey.of(9, {}), fail_if_results_missing=True
                )
                .run()
            )


class TestTableRepository:
    """Parquet-table repository (SparkTableMetricsRepository analog,
    SURVEY.md §2.5): append-only files, last-write-wins dedupe at read,
    result_key pushdown on point lookups."""

    def test_round_trip_and_overwrite_semantics(self, context, tmp_path):
        from deequ_tpu.analyzers import AnalysisRunner
        from deequ_tpu.repository.table import TableMetricsRepository

        repo = TableMetricsRepository(os.path.join(tmp_path, "tbl"))
        key = ResultKey.of(100, {"run": "r1"})
        repo.save(AnalysisResult(key, context))  # Size == 5
        # re-save the SAME key with DIFFERENT content: the newer write
        # must win regardless of (uuid-random) file enumeration order
        v2 = AnalysisRunner.do_analysis_run(
            Dataset.from_pydict({"x": [1.0, 2.0, 3.0]}), [Size()]
        )
        repo.save(AnalysisResult(key, v2))  # Size == 3
        repo2 = TableMetricsRepository(os.path.join(tmp_path, "tbl"))
        loaded = repo2.load_by_key(key)
        assert loaded is not None
        assert loaded.analyzer_context.metric(Size()).value.get() == 3.0
        assert len(repo2.load().get()) == 1  # last write per key wins

    def test_concurrent_style_appends_and_query(self, context, tmp_path):
        from deequ_tpu.repository.table import TableMetricsRepository

        path = os.path.join(tmp_path, "tbl")
        # two independent writers (as from two hosts) never conflict
        w1, w2 = TableMetricsRepository(path), TableMetricsRepository(path)
        for t, env, repo in [(10, "dev", w1), (20, "prod", w2), (30, "prod", w1)]:
            repo.save(AnalysisResult(ResultKey.of(t, {"env": env}), context))
        reader = TableMetricsRepository(path)
        got = reader.load().after(15).get()
        assert [r.result_key.dataset_date for r in got] == [20, 30]
        assert (
            len(reader.load().with_tag_values({"env": "prod"}).get()) == 2
        )

    def test_stray_files_and_tmp_writes_ignored(self, context, tmp_path):
        """Loads select *.parquet only: in-flight .tmp files (the
        atomic-rename window) and stray non-parquet files must not
        break or pollute reads (ADVICE r3 medium)."""
        from deequ_tpu.repository.table import TableMetricsRepository

        path = os.path.join(tmp_path, "tbl")
        repo = TableMetricsRepository(path)
        repo.save(AnalysisResult(ResultKey.of(1, {}), context))
        # simulate a concurrent writer mid-save + unrelated junk
        with open(os.path.join(path, ".inflight.parquet.tmp"), "wb") as f:
            f.write(b"partial parquet bytes")
        with open(os.path.join(path, "README.txt"), "w") as f:
            f.write("not a parquet file")
        reader = TableMetricsRepository(path)
        got = reader.load().get()
        assert len(got) == 1
        assert reader.load_by_key(ResultKey.of(1, {})) is not None


class TestConcurrency:
    """SURVEY §5.2: the reference's only shared mutable state is the
    in-memory provider/repository pair (ConcurrentHashMap there); both
    must tolerate concurrent writers here."""

    def test_state_provider_concurrent_writers(self):
        import threading

        from deequ_tpu.io import InMemoryStateProvider
        from deequ_tpu.analyzers import Mean, Size
        from deequ_tpu.analyzers.states import SumState

        provider = InMemoryStateProvider()
        errors = []

        def writer(col):
            try:
                a = Mean(col)
                for i in range(200):
                    provider.persist(a, SumState(float(i), i))
                    provider.load(a)
                    provider.load(Size())
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=writer, args=(f"c{j}",))
            for j in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        for j in range(4):
            state = provider.load(Mean(f"c{j}"))
            assert state is not None and int(state.count) == 199

    def test_concurrent_saves_and_loads(self, context):
        import threading

        repo = InMemoryMetricsRepository()
        errors = []

        def writer(t0):
            try:
                for i in range(50):
                    repo.save(
                        AnalysisResult(
                            ResultKey.of(t0 + i, {"w": str(t0)}), context
                        )
                    )
                    repo.load().after(0).get()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=writer, args=(base,))
            for base in (0, 1000, 2000, 3000)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert len(repo.load().get()) == 200
