"""Shared caches: resident datasets and warm compiled plans.

**DatasetCache** — the tenancy multiplier for device memory. The
engine already caches device chunks PER ``Dataset`` OBJECT (re-scans
of the same handle replay cached chunks with zero transfers;
data/table.py); what N concurrent tenants need is to reach the SAME
handle for the same table. This registry maps a caller-chosen key
(table name, parquet path, fingerprint) to one shared ``Dataset``, so
N tenants verifying one table pay ONE ``device_put`` total. Admission
awareness: each entry is weighed at registration with
``engine.scan.estimated_run_bytes`` — the same coarse estimate the
admission watermark gates on — and the registry evicts LRU-first past
its bytes watermark, never evicting a handle currently leased by an
active run (pin counts).

**PlanCache** — the service-level ledger over the engine's cross-run
jitted plan cache (engine/scan.py ``_PLAN_CACHE``). The engine cache
does the actual sharing; this ledger answers the operator's questions:
which plan tokens were warmed at startup, how many runs hit warm plans
vs recompiled, is steady state really compile-free (the acceptance
criterion "zero recompiles after warmup"). It reads per-run counter
DELTAS from telemetry run summaries, so it composes with any executor
that wraps runs in ``telemetry.run()``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from deequ_tpu.telemetry import get_telemetry


class DatasetCache:
    """Key -> shared resident ``Dataset`` handle, LRU + bytes
    watermark, pin-counted leases."""

    def __init__(self, watermark_bytes: int = 0):
        self.watermark_bytes = int(watermark_bytes)
        self._lock = threading.Lock()
        # key -> (dataset, estimated_bytes, pins)
        self._entries: "OrderedDict[str, List[Any]]" = OrderedDict()

    def _tm(self):
        return get_telemetry()

    def lease(
        self, key: str, factory: Callable[[], Any]
    ) -> Tuple[Any, bool]:
        """The shared handle for ``key`` (building it via ``factory``
        on first use), pinned until ``release(key)``. Returns
        ``(dataset, hit)``."""
        tm = self._tm()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry[2] += 1
                tm.counter("service.dataset_cache.hits").inc()
                return entry[0], True
        # build OUTSIDE the lock (factories read parquet, synthesize
        # tables); racing builders are reconciled below — first one in
        # wins, the loser's handle is dropped before any device bytes
        # are placed (placement happens at first scan, not construction)
        dataset = factory()
        from deequ_tpu.engine.scan import estimated_run_bytes

        try:
            est = int(estimated_run_bytes(dataset))
        except Exception:  # noqa: BLE001 — unsized source: weightless
            est = 0
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry[2] += 1
                tm.counter("service.dataset_cache.hits").inc()
                return entry[0], True
            self._entries[key] = [dataset, est, 1]
            tm.counter("service.dataset_cache.misses").inc()
            self._evict_locked()
            self._set_bytes_gauge_locked()
        return dataset, False

    def release(self, key: str) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry[2] = max(0, entry[2] - 1)
            self._evict_locked()
            self._set_bytes_gauge_locked()

    def _evict_locked(self) -> None:
        if self.watermark_bytes <= 0:
            return
        tm = self._tm()
        while self._bytes_locked() > self.watermark_bytes:
            victim = next(
                (
                    k
                    for k, (_ds, _b, pins) in self._entries.items()
                    if pins == 0
                ),
                None,
            )
            if victim is None:
                return  # everything pinned: over watermark but safe
            dataset, est, _ = self._entries.pop(victim)
            try:
                dataset.clear_device_cache()
            except Exception:  # noqa: BLE001 — eviction is best-effort
                pass
            tm.counter("service.dataset_cache.evictions").inc()
            tm.event(
                "service_dataset_evicted",
                dataset_key=victim,
                estimated_bytes=est,
            )

    def _bytes_locked(self) -> int:
        return sum(e[1] for e in self._entries.values())

    def _set_bytes_gauge_locked(self) -> None:
        self._tm().metrics.gauge("service.dataset_cache.bytes").set(
            self._bytes_locked()
        )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": {
                    k: {"estimated_bytes": b, "pins": p}
                    for k, (_ds, b, p) in self._entries.items()
                },
                "total_bytes": self._bytes_locked(),
                "watermark_bytes": self.watermark_bytes,
            }

    def clear(self) -> None:
        with self._lock:
            for dataset, _b, _p in self._entries.values():
                try:
                    dataset.clear_device_cache()
                except Exception:  # noqa: BLE001
                    pass
            self._entries.clear()
            self._set_bytes_gauge_locked()


class PlanCache:
    """Warm-plan ledger: tokens warmed at startup + per-run hit/compile
    accounting from telemetry run-summary counter deltas."""

    def __init__(self):
        self._lock = threading.Lock()
        self._warmed: List[str] = []
        self._runs = 0
        self._warm_runs = 0
        self._recompile_runs = 0
        # per placement shape (engine.plan_cache.per_shape.<label>.*
        # counter deltas): label -> [hits, misses]. The elastic
        # acceptance question is per-shape: "is EVERY slice size the
        # policy can choose compile-free?"
        self._per_shape: Dict[str, List[int]] = {}

    def note_warmed(self, tokens) -> None:
        tm = get_telemetry()
        with self._lock:
            for token in tokens:
                if token and token not in self._warmed:
                    self._warmed.append(token)
            n = len(self._warmed)
        tm.metrics.gauge("service.plan_cache.warmed").set(n)
        tm.event("service_plans_warmed", tokens=list(tokens))

    def record_run(self, summary: Optional[Dict[str, Any]]) -> None:
        """Fold one finished run's telemetry summary (counter DELTAS)
        into the ledger: any ``engine.plan_cache.misses`` during the
        run means it compiled something — a recompile-after-warmup in
        steady state."""
        counters = (summary or {}).get("counters", {}) or {}
        hits = int(counters.get("engine.plan_cache.hits", 0))
        misses = int(counters.get("engine.plan_cache.misses", 0))
        prefix = "engine.plan_cache.per_shape."
        shape_deltas: List[Tuple[str, int, int]] = []
        for name, value in counters.items():
            if not name.startswith(prefix):
                continue
            tail = name[len(prefix):]
            label, _, kind = tail.rpartition(".")
            if kind == "hits":
                shape_deltas.append((label, int(value), 0))
            elif kind == "misses":
                shape_deltas.append((label, 0, int(value)))
        tm = get_telemetry()
        with self._lock:
            self._runs += 1
            if misses:
                self._recompile_runs += 1
            elif hits:
                self._warm_runs += 1
            for label, h, m in shape_deltas:
                cell = self._per_shape.setdefault(label, [0, 0])
                cell[0] += h
                cell[1] += m
        if misses:
            tm.counter("service.plan_cache.recompiles").inc(misses)
        if hits:
            tm.counter("service.plan_cache.warm_hits").inc(hits)

    @property
    def warmed_tokens(self) -> List[str]:
        with self._lock:
            return list(self._warmed)

    def snapshot(self) -> Dict[str, Any]:
        from deequ_tpu.engine.scan import plan_cache_snapshot

        with self._lock:
            return {
                "warmed_tokens": list(self._warmed),
                "runs": self._runs,
                "warm_runs": self._warm_runs,
                "recompile_runs": self._recompile_runs,
                "engine_resident_plans": len(plan_cache_snapshot()),
                "per_shape": {
                    label: {"hits": cell[0], "misses": cell[1]}
                    for label, cell in sorted(self._per_shape.items())
                },
            }
